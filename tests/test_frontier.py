"""Device-resident frontier B&B (`backends/tpu/frontier.py`).

Differential tests against the Python oracle pin both the verdict AND the
confirmed-minimal-quorum count: equality of the counts on safe networks is
an enumeration-completeness check, not just a verdict check (a frontier
that silently dropped states could still luck into the right verdict)."""

from __future__ import annotations

import json
import subprocess
import sys

import pytest

from quorum_intersection_tpu.backends.python_oracle import PythonOracleBackend
from quorum_intersection_tpu.backends.tpu.frontier import (
    FrontierSearchInterrupted,
    TpuFrontierBackend,
)
from quorum_intersection_tpu.fbas.synth import (
    hierarchical_fbas,
    majority_fbas,
    random_fbas,
)
from quorum_intersection_tpu.pipeline import solve


def _pair(data, **frontier_kw):
    po = solve(data, backend=PythonOracleBackend())
    fr = solve(data, backend=TpuFrontierBackend(**frontier_kw))
    return po, fr


class TestDifferential:
    @pytest.mark.parametrize("n", [7, 9, 11])
    def test_majority_safe(self, n):
        po, fr = _pair(majority_fbas(n), arena=8192, pop=256)
        assert fr.intersects is True and po.intersects is True
        assert fr.stats["minimal_quorums"] == po.stats["minimal_quorums"]

    @pytest.mark.parametrize("n", [8, 10, 12])
    def test_majority_broken(self, n):
        po, fr = _pair(majority_fbas(n, broken=True), arena=8192, pop=256)
        assert fr.intersects is False and po.intersects is False
        assert fr.q1 and fr.q2 and not set(fr.q1) & set(fr.q2)

    def test_hierarchical_flag_path(self):
        # Hierarchical networks flag dontRemove-quorum states (the host
        # minimality path); count parity proves none were lost or invented.
        po, fr = _pair(hierarchical_fbas(4, 3), arena=8192, pop=256)
        assert fr.intersects is True
        assert fr.stats["flagged"] > 0
        assert fr.stats["minimal_quorums"] == po.stats["minimal_quorums"] > 0

    @pytest.mark.parametrize("seed", range(6))
    def test_random_differential(self, seed):
        po, fr = _pair(random_fbas(13, seed=seed), arena=8192, pop=256)
        assert po.intersects == fr.intersects
        if po.stats.get("reason") != "scc_guard" and po.intersects:
            assert fr.stats["minimal_quorums"] == po.stats["minimal_quorums"]

    def test_scope_to_scc(self):
        from quorum_intersection_tpu.encode.circuit import encode_circuit
        from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
        from quorum_intersection_tpu.fbas.schema import parse_fbas

        graph = build_graph(parse_fbas(majority_fbas(10, broken=True)))
        count, comp = tarjan_scc(graph.n, graph.succ)
        scc = max(group_sccs(graph.n, comp, count), key=len)
        circuit = encode_circuit(graph)
        po = PythonOracleBackend().check_scc(graph, None, scc, scope_to_scc=True)
        fr = TpuFrontierBackend(arena=4096, pop=128).check_scc(
            graph, circuit, scc, scope_to_scc=True
        )
        assert po.intersects == fr.intersects is False


class TestArenaSpill:
    def test_tiny_arena_forces_spill(self):
        # A 64-slot arena with 16-state pops overflows on hier-4x3's tree
        # (measured: ~22 spills) and must still enumerate everything
        # (count parity).
        po, fr = _pair(hierarchical_fbas(4, 3), arena=64, pop=16)
        assert fr.intersects is True
        assert fr.stats["spills"] > 0
        assert fr.stats["minimal_quorums"] == po.stats["minimal_quorums"]

    def test_tiny_arena_broken_verdict(self):
        _, fr = _pair(majority_fbas(12, broken=True), arena=128, pop=16)
        assert fr.intersects is False
        assert fr.q1 and fr.q2 and not set(fr.q1) & set(fr.q2)

    def test_degenerate_arena_rejected(self):
        # arena < 4 would clamp pop to 0 and spin the chunk loop forever;
        # the constructor must reject it like the mesh path rejects
        # arena < 4 * n_dev.
        for arena in (-8, 0, 1, 3):
            with pytest.raises(ValueError):
                TpuFrontierBackend(arena=arena, pop=16)


class TestCheckpoint:
    def _ck(self, tmp_path):
        from quorum_intersection_tpu.utils.checkpoint import FrontierCheckpoint

        return FrontierCheckpoint(tmp_path / "frontier.ckpt")

    def test_kill_resume_same_verdict(self, tmp_path):
        ck = self._ck(tmp_path)
        with pytest.raises(FrontierSearchInterrupted):
            solve(
                hierarchical_fbas(4, 3),
                backend=TpuFrontierBackend(
                    arena=2048, pop=64, chunk_iters=2, checkpoint=ck,
                    interrupt_after_chunks=2,
                ),
            )
        assert ck.path.exists()
        resumed = solve(
            hierarchical_fbas(4, 3),
            backend=TpuFrontierBackend(arena=2048, pop=64, checkpoint=ck),
        )
        assert resumed.intersects is True
        assert resumed.stats.get("resumed_states", 0) > 0
        assert not ck.path.exists()  # cleared on completion

    def test_stale_checkpoint_rejected(self, tmp_path):
        ck = self._ck(tmp_path)
        with pytest.raises(FrontierSearchInterrupted):
            solve(
                hierarchical_fbas(4, 3),
                backend=TpuFrontierBackend(
                    arena=2048, pop=64, chunk_iters=2, checkpoint=ck,
                    interrupt_after_chunks=2,
                ),
            )
        # Different problem, same file: the fingerprint must reject it.
        other = solve(
            majority_fbas(9),
            backend=TpuFrontierBackend(arena=2048, pop=64, checkpoint=ck),
        )
        assert other.intersects is True
        assert "resumed_states" not in other.stats


class TestCli:
    def test_cli_frontier_backend(self, ref_fixture):
        proc = subprocess.run(
            [sys.executable, "-m", "quorum_intersection_tpu",
             "--backend", "tpu-frontier"],
            input=ref_fixture("broken.json").read_text(),
            capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 1, proc.stderr
        assert proc.stdout == "false\n"

    def test_cli_frontier_checkpoint_flag(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, "-m", "quorum_intersection_tpu",
             "--backend", "tpu-frontier", "--checkpoint", str(tmp_path / "f.ckpt")],
            input=json.dumps(majority_fbas(9)),
            capture_output=True, text=True, timeout=240,
        )
        assert proc.returncode == 0, proc.stderr
        assert proc.stdout == "true\n"


class TestHostChecker:
    @pytest.mark.parametrize("scope_to_scc", [False, True])
    def test_native_and_python_checkers_agree(self, scope_to_scc):
        # The flagged-set host check has two engines (native qi_max_quorum /
        # Python semantics); they must return identical (minimal, witness)
        # on realistic flagged sets: every subset the hier search flags plus
        # adversarial non-minimal supersets.
        from quorum_intersection_tpu.fbas.graph import (
            build_graph,
            group_sccs,
            tarjan_scc,
        )
        from quorum_intersection_tpu.fbas.schema import parse_fbas
        graph = build_graph(parse_fbas(hierarchical_fbas(3, 3, broken=False)))
        count, comp = tarjan_scc(graph.n, graph.succ)
        scc = max(group_sccs(graph.n, comp, count), key=len)
        backend = TpuFrontierBackend()
        try:
            from quorum_intersection_tpu.backends.cpp import NativeMaxQuorum

            NativeMaxQuorum(graph)  # skip cleanly when g++ unavailable
        except Exception:
            pytest.skip("native library unavailable")
        native = backend._make_host_checker(graph, scc, scope_to_scc)
        import itertools

        for r in (2, 3, 4, 5):
            for members in itertools.islice(itertools.combinations(scc, r), 40):
                got = native(list(members))
                want = backend._host_witness_check(
                    graph, scc, list(members), scope_to_scc
                )
                assert got[0] == want[0], members
                assert (got[1] is None) == (want[1] is None), members


class TestDeterminism:
    def test_repeated_runs_identical_witness(self):
        # Deterministic branch choice (lowest-index argmax) + FIFO-ordered
        # flag processing ⇒ byte-identical witnesses run to run.
        a = solve(majority_fbas(12, broken=True),
                  backend=TpuFrontierBackend(arena=2048, pop=128))
        b = solve(majority_fbas(12, broken=True),
                  backend=TpuFrontierBackend(arena=2048, pop=128))
        assert a.intersects is b.intersects is False
        assert a.q1 == b.q1 and a.q2 == b.q2


class TestResumeSpill:
    def test_resume_frontier_larger_than_arena(self, tmp_path):
        # A checkpoint written under a BIG arena can hold more states than
        # the resuming backend's arena//2; the excess must re-feed through
        # the host spill in blocks, with count parity intact.
        from quorum_intersection_tpu.utils.checkpoint import FrontierCheckpoint

        ck = FrontierCheckpoint(tmp_path / "f.ckpt")
        with pytest.raises(FrontierSearchInterrupted):
            solve(
                hierarchical_fbas(4, 3),
                backend=TpuFrontierBackend(
                    arena=4096, pop=128, chunk_iters=4, checkpoint=ck,
                    interrupt_after_chunks=2,
                ),
            )
        resumed = solve(
            hierarchical_fbas(4, 3),
            backend=TpuFrontierBackend(arena=64, pop=16, checkpoint=ck),
        )
        assert resumed.intersects is True
        assert resumed.stats.get("resumed_states", 0) > 0
        # Full-search count parity would need the pre-interrupt quorums too;
        # the strong invariant here is completion + no crash through the
        # block-spill resume path and a clean final checkpoint.
        assert not ck.path.exists()


class TestDeviceFlagFilter:
    """The batched device flag pipeline (`flag_check="device"`): leave-one-out
    minimality + disjointness probe as device fixpoints, host re-verifying
    only witness candidates.  Forced on explicitly (tests run on the CPU
    platform, where `auto` would pick the serial host path)."""

    def test_count_parity_vs_oracle(self):
        po, fr = _pair(
            hierarchical_fbas(5, 3), arena=8192, pop=256, flag_check="device"
        )
        assert po.intersects is fr.intersects is True
        assert fr.stats["minimal_quorums"] == po.stats["minimal_quorums"]
        assert fr.stats["device_flag_checks"] == fr.stats["flagged"]
        assert fr.stats["host_checks"] == 0  # safe: nothing to re-verify

    def test_broken_witness_single_host_reverify(self):
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        data = stellar_like_fbas(
            n_core_orgs=4, per_org=3, n_watchers=10, broken=True
        )
        po, fr = _pair(data, arena=8192, pop=256, flag_check="device")
        assert po.intersects is fr.intersects is False
        assert fr.q1 and fr.q2 and not set(fr.q1) & set(fr.q2)
        # The device filter hands the host exactly one witness candidate.
        assert fr.stats["host_checks"] == 1

    def test_spill_path(self):
        po, fr = _pair(
            hierarchical_fbas(4, 3), arena=64, pop=16, flag_check="device"
        )
        assert fr.intersects is True
        assert fr.stats["spills"] > 0
        assert fr.stats["minimal_quorums"] == po.stats["minimal_quorums"]

    @pytest.mark.parametrize("seed", range(4))
    def test_random_differential(self, seed):
        data = random_fbas(14, seed=seed, nested_prob=0.3, null_prob=0.1)
        po, fr = _pair(data, arena=4096, pop=128, flag_check="device")
        assert po.intersects is fr.intersects

    def test_bad_mode_rejected(self):
        with pytest.raises(ValueError):
            TpuFrontierBackend(flag_check="gpu")


class TestRestrictedCheckpoint:
    def test_checkpoint_on_wide_graph(self, tmp_path):
        # Regression: the checkpoint fingerprint must build its masks in
        # the RESTRICTED circuit's index space — graph-space SCC ids
        # crashed with IndexError when the graph is wider than the SCC.
        from quorum_intersection_tpu.fbas.synth import benchmark_fbas
        from quorum_intersection_tpu.utils.checkpoint import FrontierCheckpoint

        data = benchmark_fbas(64, 14, seed=1)
        ck = FrontierCheckpoint(tmp_path / "wide_frontier.json")
        res = solve(
            data,
            backend=TpuFrontierBackend(arena=4096, pop=128, checkpoint=ck),
        )
        assert res.intersects is True

    def test_kill_resume_on_wide_graph(self, tmp_path):
        # The full preemption round-trip on a restricted circuit: interrupt
        # after one chunk, resume from the written frontier, same verdict
        # and a completed enumeration.
        from quorum_intersection_tpu.fbas.synth import benchmark_fbas
        from quorum_intersection_tpu.utils.checkpoint import FrontierCheckpoint

        data = benchmark_fbas(48, 13, seed=4)
        po = solve(data, backend="python")
        ck = FrontierCheckpoint(tmp_path / "wide_resume.json")
        with pytest.raises(FrontierSearchInterrupted):
            solve(data, backend=TpuFrontierBackend(
                arena=1024, pop=32, chunk_iters=2, checkpoint=ck,
                interrupt_after_chunks=1,
            ))
        res = solve(data, backend=TpuFrontierBackend(
            arena=1024, pop=32, checkpoint=ck,
        ))
        assert res.intersects is po.intersects
