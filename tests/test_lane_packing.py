"""Differential parity suite for the lane-packed sweep (ISSUE 5).

The contract under test: packing K problems into one block-diagonal MXU
block changes SCHEDULING ONLY — verdict, witness pair, and first-hit index
must be byte-identical to running the unpacked sweep per problem, and both
must agree with the python oracle.  Plus the packing invariants
(block-diagonal inertness, decode-map contract — docs/PARITY.md), the
work-accounting claim the bench row makes checkable off-chip, and the
``sweep.pack`` fault degrading to the unpacked sweep with the verdict
unchanged.
"""

import json

import numpy as np
import pytest

from quorum_intersection_tpu.backends.tpu.sweep import (
    EngineResolution,
    TpuSweepBackend,
    macs_per_candidate_row,
    resolve_engine,
)
from quorum_intersection_tpu.encode.circuit import (
    LANE_TILE,
    encode_circuit,
    node_sat_np,
    pack_circuits,
    plan_packs,
    restrict_circuit_pair,
)
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import hierarchical_fbas
from quorum_intersection_tpu.pipeline import check_many, quorum_bearing_sccs, solve


def kofn(n, k, prefix="N"):
    """Symmetric k-of-n FBAS: one SCC; broken (two disjoint quorums) iff
    k <= n // 2 — the broken twin the sweep itself must find, unlike the
    synth ``broken=True`` pairs whose degenerate node splits into its own
    quorum-bearing SCC and is guard-decided before any backend runs."""
    ks = [f"{prefix}{i}" for i in range(n)]
    return [
        {"publicKey": x, "name": x, "quorumSet": {"threshold": k, "validators": ks}}
        for x in ks
    ]


def make_job(data):
    graph = build_graph(parse_fbas(data))
    circuit = encode_circuit(graph)
    bearing = quorum_bearing_sccs(graph, allow_native=False)
    assert len(bearing) == 1, "fixture must have exactly one quorum-bearing SCC"
    return graph, circuit, bearing[0][1]


# Every fixture pair: (correct, broken) twins that reach the backend.
PAIRS = [
    (kofn(8, 5), kofn(8, 4)),
    (kofn(11, 6, "Q"), kofn(11, 5, "Q")),
    (hierarchical_fbas(3, 3), hierarchical_fbas(3, 4, org_threshold=1)),
]


def assert_parity(unpacked, packed):
    assert unpacked.intersects == packed.intersects
    assert unpacked.q1 == packed.q1
    assert unpacked.q2 == packed.q2
    assert unpacked.stats.get("hit_index") == packed.stats.get("hit_index")


class TestPackedCircuitInvariants:
    def test_block_diagonal_inertness_and_layout(self):
        members = []
        for data in [kofn(6, 4), hierarchical_fbas(3, 3), kofn(9, 5, "B")]:
            graph, circuit, scc = make_job(data)
            scoped, q6 = restrict_circuit_pair(circuit, scc)
            members.append((scoped, q6))
        packed = pack_circuits(members)
        slot = packed.slot
        n = packed.circuit.n
        for g, (scoped, _) in enumerate(members):
            base = g * slot
            cols = np.zeros(n, dtype=bool)
            cols[base : base + scoped.n] = True
            rows = np.zeros(packed.circuit.n_units, dtype=bool)
            rows[base : base + scoped.n] = True  # root units mirror lanes
            # Root-unit layout: unit base+j is node base+j's quorum set.
            np.testing.assert_array_equal(
                packed.circuit.members[base : base + scoped.n, base : base + scoped.n],
                scoped.members[: scoped.n, :],
            )
            np.testing.assert_array_equal(
                packed.circuit.thresholds[base : base + scoped.n],
                scoped.thresholds[: scoped.n],
            )
            # Cross-block inertness: group g's unit rows carry zero votes
            # outside group g's lane columns.
            np.testing.assert_array_equal(
                packed.circuit.members[np.ix_(rows, ~cols)], 0
            )
        # Decode-map contract.
        pos, scc_mask, lane_group, group_ind = packed.decode_tables()
        for g, (scoped, _) in enumerate(members):
            base = g * slot
            assert pos[base] == 31  # local node 0 fixed out
            assert list(pos[base + 1 : base + scoped.n]) == list(range(scoped.n - 1))
            assert scc_mask[base : base + scoped.n].all()
            assert (lane_group[base : base + scoped.n] == g).all()
            assert group_ind[base : base + scoped.n, g].all()
        assert group_ind.sum() == sum(packed.sizes)
        assert 0 < packed.fill_pct <= 100.0

    def test_packed_fixpoint_matches_members(self):
        """Block-diagonal inertness, functionally: the fused node_sat equals
        each member's own node_sat on its lane slice, for random avails."""
        members = []
        for data in [kofn(7, 4), hierarchical_fbas(3, 3)]:
            graph, circuit, scc = make_job(data)
            scoped, q6 = restrict_circuit_pair(circuit, scc)
            members.append((scoped, q6))
        packed = pack_circuits(members)
        rng = np.random.default_rng(0)
        avail = np.zeros((16, packed.circuit.n), dtype=bool)
        for g, (scoped, _) in enumerate(members):
            base = g * packed.slot
            avail[:, base : base + scoped.n] = rng.random((16, scoped.n)) < 0.6
        got = node_sat_np(packed.circuit, avail)
        for g, (scoped, _) in enumerate(members):
            base = g * packed.slot
            want = node_sat_np(scoped, avail[:, base : base + scoped.n])
            np.testing.assert_array_equal(got[:, base : base + scoped.n], want)
        # Padded lanes stay identically zero.
        mask = np.zeros(packed.circuit.n, dtype=bool)
        for g, (scoped, _) in enumerate(members):
            mask[g * packed.slot : g * packed.slot + scoped.n] = True
        assert not got[:, ~mask].any()

    def test_plan_packs_capacity_and_solo(self):
        # 9 small jobs at slot 16 -> capacity 8: one full pack + ragged tail.
        packs = plan_packs([12, 9, 10, 13, 11, 9, 12, 10, 9])
        assert sorted(len(p) for p in packs) == [1, 8]
        assert sorted(i for p in packs for i in p) == list(range(9))
        # A job wider than the tile goes solo.
        packs = plan_packs([LANE_TILE + 1, 8, 8])
        assert [len(p) for p in packs] == [1, 2]


class TestPackedSweepParity:
    @pytest.mark.parametrize("engine", ["xla", "pallas"])
    def test_mixed_pack_matches_unpacked_and_oracle(self, engine):
        datas = [d for pair in PAIRS for d in pair]
        jobs = [make_job(d) for d in datas]
        unpacked = [
            TpuSweepBackend(batch=256).check_scc(g, c, s) for g, c, s in jobs
        ]
        packed = TpuSweepBackend(batch=256, engine=engine).check_sccs(jobs)
        for data, u, p in zip(datas, unpacked, packed):
            assert_parity(u, p)
            assert p.stats["packed"] is True
            assert p.stats["pack_engine"] == engine
            oracle = solve(data, backend="python")
            assert oracle.intersects == p.intersects

    def test_k1_degenerate(self):
        # One tiny job: a single lane group (no window split below two
        # blocks), still through the packed path, same verdict.
        graph, circuit, scc = make_job(kofn(5, 3))
        unpacked = TpuSweepBackend(batch=256).check_scc(graph, circuit, scc)
        (packed,) = TpuSweepBackend(batch=256).check_sccs([(graph, circuit, scc)])
        assert_parity(unpacked, packed)
        assert packed.stats["pack_groups"] == 1

    @pytest.mark.parametrize("broken", [False, True])
    def test_window_split_single_scc(self, broken):
        # One 16-node job, spare lanes: the enumeration splits into
        # multiple in-flight windows (pack source (a)); the first-hit index
        # must still be the global minimum, as the unpacked FIFO finds it.
        data = kofn(16, 8 if broken else 9, "W")
        graph, circuit, scc = make_job(data)
        unpacked = TpuSweepBackend(batch=256).check_scc(graph, circuit, scc)
        (packed,) = TpuSweepBackend(batch=256).check_sccs([(graph, circuit, scc)])
        assert_parity(unpacked, packed)
        assert packed.stats["pack_groups"] > 1

    def test_ragged_last_pack(self):
        # 9 jobs at capacity 8: two packs, the second ragged; order and
        # verdicts preserved.
        datas = [kofn(9 + (i % 4), 5 + (i % 2), f"R{i}") for i in range(9)]
        jobs = [make_job(d) for d in datas]
        unpacked = [
            TpuSweepBackend(batch=256).check_scc(g, c, s) for g, c, s in jobs
        ]
        packed = TpuSweepBackend(batch=256).check_sccs(jobs)
        for u, p in zip(unpacked, packed):
            assert_parity(u, p)

    def test_cancel_token_pre_cancelled(self):
        from quorum_intersection_tpu.backends.base import CancelToken, SearchCancelled

        cancel = CancelToken()
        cancel.cancel()
        graph, circuit, scc = make_job(kofn(8, 5))
        with pytest.raises(SearchCancelled):
            TpuSweepBackend(batch=256, cancel=cancel).check_sccs(
                [(graph, circuit, scc)]
            )


class TestCheckMany:
    def test_check_many_matches_solo_solve(self):
        # Mix: sweep-eligible jobs, a guard-decided broken source (the
        # degenerate node splits into its own quorum-bearing SCC), and a
        # correct hierarchical network.
        datas = [kofn(8, 5), kofn(8, 4), hierarchical_fbas(3, 3, broken=True),
                 hierarchical_fbas(3, 3)]
        many = check_many(datas, backend=TpuSweepBackend(batch=256))
        for data, res in zip(datas, many):
            solo = solve(data, backend="python")
            assert res.intersects == solo.intersects
        # The guard-decided source never reached the backend.
        assert many[2].stats.get("reason") == "scc_guard"
        assert many[2].q1 and many[2].q2

    def test_check_many_auto_forced_pack(self):
        datas = [kofn(8, 5), kofn(8, 4), hierarchical_fbas(3, 3)]
        many = check_many(datas, backend="auto", pack=True)
        for data, res in zip(datas, many):
            assert res.intersects == solve(data, backend="python").intersects
            assert res.stats.get("packed") is True
            assert res.stats.get("backend") == "tpu-sweep"


class TestPackFaultDegrade:
    def test_injected_pack_fault_degrades_to_unpacked(self, monkeypatch):
        monkeypatch.setenv("QI_FAULTS", "sweep.pack=error")
        datas = [kofn(8, 5), kofn(8, 4)]
        many = check_many(datas, backend="auto", pack=True)
        for data, res in zip(datas, many):
            assert res.intersects == solve(data, backend="python").intersects
            # The packed engine never answered; the per-problem router did.
            assert not res.stats.get("packed")
        from quorum_intersection_tpu.utils.telemetry import get_run_record

        rec = get_run_record()
        degrades = [
            e for e in rec.events
            if e.get("name") == "degrade"
            and "sweep.pack" in str(e.get("attrs", {}).get("cause", ""))
        ]
        assert degrades, "expected a ladder degrade event for the pack fault"
        assert any(
            e.get("name") == "fault.injected"
            and e.get("attrs", {}).get("point") == "sweep.pack"
            for e in rec.events
        )


class TestWorkAccounting:
    def test_packed_macs_per_verdict_at_most_half(self):
        """The acceptance-criterion accounting, checkable off-chip: for
        K >= 2 circuits with n <= 48, packed MACs-per-verdict (lane-padded
        shape model x rows actually dispatched, shared across the pack's
        verdicts) is at most half the unpacked sum."""
        datas = [kofn(12, 7, "A"), kofn(12, 6, "B"),
                 kofn(12, 7, "C"), kofn(12, 6, "D")]
        jobs = [make_job(d) for d in datas]
        unpacked = [
            TpuSweepBackend(batch=256).check_scc(g, c, s) for g, c, s in jobs
        ]
        packed = TpuSweepBackend(batch=256).check_sccs(jobs)
        k = len(jobs)
        pstats = packed[0].stats
        assert pstats["pack_jobs"] == k
        packed_macs_per_verdict = (
            pstats["pack_rows_dispatched"]
            * pstats["pack_macs_per_candidate_row"] / k
        )
        unpacked_total = 0.0
        for res in unpacked:
            shape = res.stats.get("padded_shape") or res.stats["device_shape"]
            unpacked_total += res.stats["candidates_checked"] * macs_per_candidate_row(
                shape[0], shape[1], 0
            )
        assert unpacked_total > 0
        ratio = packed_macs_per_verdict / (unpacked_total / k)
        assert ratio <= 0.5, f"packed MACs ratio {ratio:.3f} > 1/2"


class TestPackGate:
    def test_pack_win_parser_loss_cap(self, tmp_path):
        """A measured loss above a win caps the window — the sweep-window
        discipline: headroom must never route a measured-slower size."""
        from quorum_intersection_tpu.backends.calibration import _pack_win_max_scc

        art = tmp_path / "sweep_vs_native_cpu_r9.txt"
        rows = [
            {"scc": 12, "device": "cpu",
             "packed_speedup_vs_unpacked": 2.2, "verdict_ok": True},
            {"scc": 14, "device": "cpu",
             "packed_speedup_vs_unpacked": 0.8, "verdict_ok": True},
            {"scc": 16, "device": "cpu",
             "packed_speedup_vs_unpacked": 1.1, "verdict_ok": True},
        ]
        art.write_text("\n".join(json.dumps(r) for r in rows))
        win, kind, _ = _pack_win_max_scc([art])
        assert (win, kind) == (12, "cpu")

    def test_pack_win_parser_partitions_device_kinds(self, tmp_path):
        """CPU-emulated rows never merge into (or mislabel) a chip window;
        when both kinds win, the accelerator's gate is the one recorded."""
        from quorum_intersection_tpu.backends.calibration import _pack_win_max_scc

        art = tmp_path / "sweep_vs_native_tpu_r9.txt"
        rows = [
            {"scc": 12, "device": "cpu",
             "packed_speedup_vs_unpacked": 2.5, "verdict_ok": True},
            {"scc": 20, "device": "TPU v5 lite",
             "packed_speedup_vs_unpacked": 1.4, "verdict_ok": True},
            {"scc": 24, "device": "TPU v5 lite",
             "packed_speedup_vs_unpacked": 0.7, "verdict_ok": True},
        ]
        art.write_text("\n".join(json.dumps(r) for r in rows))
        win, kind, _ = _pack_win_max_scc([art])
        assert (win, kind) == (20, "tpu")

    def test_pack_bound_caps_auto_gated_sizes(self, monkeypatch):
        """Auto-gated packing caps PER-JOB sizes at the measured window +
        headroom — engagement off two small jobs must not sneak an
        unmeasured size into the pack.  The bound is PROBE-FREE (no device
        contact before the budgeted oracles run); the device-kind half of
        the gate is applied in check_sccs after every oracle answered."""
        from quorum_intersection_tpu.backends import calibration
        from quorum_intersection_tpu.backends.auto import (
            SWEEP_WIN_SCC_HEADROOM,
            AutoBackend,
        )

        monkeypatch.setattr(calibration.CALIBRATION, "pack_win_max_scc", 12)
        monkeypatch.setattr(calibration.CALIBRATION, "pack_win_device", "cpu")
        auto = AutoBackend()
        bound = 12 + SWEEP_WIN_SCC_HEADROOM
        assert auto._pack_bound([12, 13, 18]) == bound
        assert auto._pack_bound([30, 40]) is None  # nothing in the window
        assert auto._pack_bound([12]) is None  # needs two jobs to share
        assert AutoBackend(pack=True)._pack_bound([50]) is not None  # forced
        assert AutoBackend(pack=False)._pack_bound([8, 8]) is None
        monkeypatch.setattr(calibration.CALIBRATION, "pack_win_max_scc", None)
        assert auto._pack_bound([8, 8]) is None  # no measured win on record

    def test_check_many_pack_false_never_packs(self):
        """pack=False forbids the packed path even on a backend whose
        batch entry packs unconditionally (no pack knob)."""
        datas = [kofn(8, 5), kofn(8, 4)]
        many = check_many(datas, backend=TpuSweepBackend(batch=256), pack=False)
        for data, res in zip(datas, many):
            assert res.intersects == solve(data, backend="python").intersects
            assert not res.stats.get("packed")

    def test_check_many_does_not_leak_forced_pack(self):
        """A pack=True batch on a caller-supplied backend is call-scoped."""
        from quorum_intersection_tpu.backends.auto import AutoBackend

        auto = AutoBackend()
        assert auto.pack is None
        check_many([kofn(6, 4)], backend=auto, pack=True)
        assert auto.pack is None


class TestEngineResolution:
    def test_precedence(self):
        graph, circuit, scc = make_job(kofn(8, 5))
        scoped, _ = restrict_circuit_pair(circuit, scc)
        res = resolve_engine(
            "xla", mesh=True, wide=True, restricted=True, circuit=scoped
        )
        assert res == EngineResolution("xla", "xla", "as requested")
        assert resolve_engine(
            "pallas", mesh=True, wide=False, restricted=False, circuit=scoped
        ).resolved == "xla"
        assert resolve_engine(
            "pallas", mesh=False, wide=True, restricted=False, circuit=scoped
        ).resolved == "xla"
        assert resolve_engine(
            "pallas", mesh=False, wide=False, restricted=True, circuit=scoped
        ).resolved == "xla"
        ok = resolve_engine(
            "pallas", mesh=False, wide=False, restricted=False, circuit=scoped
        )
        assert ok.resolved == "pallas" and ok.reason == "as requested"

    def test_event_emitted_on_engine_mismatch(self):
        """The old sweep.py:397 warn-and-swerve is now a typed decision
        with an explicit telemetry event (here via the restricted-sweep
        precedence rule; the mesh rule is pinned in test_precedence)."""
        from quorum_intersection_tpu.utils.telemetry import get_run_record

        # A pendant node outside the core SCC forces SCC restriction.
        data = kofn(8, 5) + [{
            "publicKey": "PENDANT", "name": "p",
            "quorumSet": {"threshold": 5, "validators": [f"N{i}" for i in range(8)]},
        }]
        graph = build_graph(parse_fbas(data))
        circuit = encode_circuit(graph)
        bearing = quorum_bearing_sccs(graph, allow_native=False)
        assert len(bearing) == 1 and len(bearing[0][1]) == 8
        before = len(get_run_record().events)
        res = TpuSweepBackend(batch=256, engine="pallas").check_scc(
            graph, circuit, bearing[0][1]
        )
        assert res.intersects is True
        resolved = [
            e for e in get_run_record().events[before:]
            if e.get("name") == "sweep.engine_resolved"
        ]
        assert resolved, "expected a sweep.engine_resolved event"
        attrs = resolved[0]["attrs"]
        assert attrs["requested"] == "pallas"
        assert attrs["resolved"] == "xla"
        assert "restricted" in attrs["reason"]
