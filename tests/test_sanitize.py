"""Sanitizer tests — reference-compat filter plus recursive/dangling upgrades."""

import json
import subprocess
import sys

from quorum_intersection_tpu.fbas.sanitize import dangling_refs, sanitize


def _node(key, qset):
    return {"publicKey": key, "quorumSet": qset}


SANE = _node("A", {"threshold": 2, "validators": ["A", "B"], "innerQuorumSets": []})
INSANE_TOP = _node("B", {"threshold": 5, "validators": ["A", "B"], "innerQuorumSets": []})
INSANE_INNER = _node(
    "C",
    {
        "threshold": 1,
        "validators": [],
        "innerQuorumSets": [{"threshold": 9, "validators": ["A"], "innerQuorumSets": []}],
    },
)
NULL_NODE = _node("D", None)


def test_compat_filter_matches_reference_semantics():
    # Reference filter (fix_quorum_configurations.py:11-15): top-level only.
    out = sanitize([SANE, INSANE_TOP, INSANE_INNER, NULL_NODE], compat=True)
    assert [n["publicKey"] for n in out] == ["A", "C", "D"]


def test_recursive_filter_catches_inner_insanity():
    out = sanitize([SANE, INSANE_TOP, INSANE_INNER, NULL_NODE])
    assert [n["publicKey"] for n in out] == ["A", "D"]


def test_null_qset_kept_not_crashed():
    # The reference script TypeErrors on null qsets (verified on its own
    # correct.json); we keep them — they are harmless (Q2).
    assert sanitize([NULL_NODE]) == [NULL_NODE]


def test_numeric_string_threshold_agrees_with_schema():
    # The sanitizer must accept what parse_fbas accepts (numeric strings).
    node = _node("S", {"threshold": "2", "validators": ["A", "B"], "innerQuorumSets": []})
    assert sanitize([node]) == [node]
    bad = _node("S", {"threshold": "two", "validators": ["A", "B"], "innerQuorumSets": []})
    assert sanitize([bad]) == []


def test_zero_threshold_flagging():
    zero = _node("Z", {"threshold": 0, "validators": [], "innerQuorumSets": []})
    assert sanitize([zero]) == [zero]
    assert sanitize([zero], flag_zero_threshold=True) == []


def test_dangling_refs_reported():
    nodes = [
        _node("A", {"threshold": 1, "validators": ["A", "GHOST"], "innerQuorumSets": []}),
        _node(
            "B",
            {
                "threshold": 1,
                "validators": [],
                "innerQuorumSets": [{"threshold": 1, "validators": ["PHANTOM"], "innerQuorumSets": []}],
            },
        ),
    ]
    assert dangling_refs(nodes) == {"GHOST", "PHANTOM"}


def test_cli_stdin_stdout_roundtrip():
    data = [SANE, INSANE_TOP]
    proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu.fbas.sanitize"],
        input=json.dumps(data),
        capture_output=True,
        text=True,
        check=True,
    )
    assert json.loads(proc.stdout) == [SANE]


def test_reference_fixture_sanitize_no_crash(ref_fixture):
    with open(ref_fixture("correct.json")) as f:
        data = json.load(f)
    out = sanitize(data, compat=True)
    assert len(out) <= len(data)
    assert all("publicKey" in n for n in out)
