"""qi-query differential suite (ISSUE 12): every query kind checked
against a stdlib oracle on the fixture pairs, whatif packed-vs-sequential
byte parity, relaxed witness certificates validated by the independent
checker, the query.dispatch fault degrade (typed, never a wrong verdict),
serve/fleet round-trips with mixed query streams, journal replay of typed
queries, the synth scale presets' seed determinism, and the fleet
respawn / shared-store GC satellites."""

import json
import tempfile
import time

import pytest

from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
from quorum_intersection_tpu.delta import SharedSccStore
from quorum_intersection_tpu.encode.circuit import (
    encode_circuit,
    max_quorum_np,
    restrict_two_family,
)
from quorum_intersection_tpu.fbas import synth
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.semantics import (
    cross_family_disjoint_quorum,
    max_quorum,
    relaxed_disjoint_witness,
)
from quorum_intersection_tpu.fleet import FleetEngine
from quorum_intersection_tpu.pipeline import quorum_bearing_sccs, solve
from quorum_intersection_tpu.query import (
    Query,
    QueryEngine,
    QueryError,
    _relaxed_search,
    mask_nodes,
)
from quorum_intersection_tpu.serve import (
    RequestJournal,
    ServeEngine,
    snapshot_fingerprint,
)
from quorum_intersection_tpu.utils import faults, telemetry
from tools.check_cert import CheckFailure, check_certificate

from tests.conftest import VENDORED_DIR

FIXTURE_PAIRS = [
    ("trivial_correct", True),
    ("trivial_broken", False),
    ("nested_correct", True),
    ("nested_broken", False),
]


def fixture_nodes(name):
    return json.loads((VENDORED_DIR / f"{name}.json").read_text())


@pytest.fixture
def rec():
    record = telemetry.reset_run_record()
    faults.clear_plan()
    yield record
    faults.clear_plan()
    telemetry.reset_run_record()


def roundtrip(obj):
    """JSON round-trip: what the serve/fleet wire would deliver."""
    return json.loads(json.dumps(obj, default=str))


def subset_oracle(nodes_a, nodes_b):
    """Independent stdlib relaxed oracle over the WHOLE node set: a
    disjoint cross-family pair exists iff some split S of all vertices
    holds a family-A quorum inside S and a family-B quorum inside its
    complement (any disjoint pair (QA, QB) induces the split S = QA ∪
    (V ∖ QB) ⊇ QA with QB ∩ S = ∅, and the converse is immediate).
    2^n host fixpoints — no SCC confinement, no guard memoization, so it
    shares nothing with the query engine's search structure."""
    ga = build_graph(parse_fbas(nodes_a))
    gb = build_graph(parse_fbas(nodes_b))
    n = ga.n
    avail = [False] * n
    for window in range(1, 1 << n):
        chosen = [v for v in range(n) if window >> v & 1]
        for v in chosen:
            avail[v] = True
        qa = max_quorum(ga, chosen, avail)
        for v in chosen:
            avail[v] = False
        if not qa:
            continue
        rest = [v for v in range(n) if v not in set(qa)]
        for v in rest:
            avail[v] = True
        qb = max_quorum(gb, rest, avail)
        for v in rest:
            avail[v] = False
        if qb:
            return False  # disjoint pair exists
    return True


# ---------------------------------------------------------------------------
# query parsing + fingerprints


class TestQueryParse:
    def test_absent_is_intersection(self):
        q = Query.parse(None)
        assert q.kind == "intersection"
        assert q.fingerprint() == ""
        assert q.to_wire() is None

    def test_unknown_kind_typed(self):
        with pytest.raises(QueryError) as exc:
            Query.parse({"kind": "bogus"})
        assert exc.value.code == "unknown_query"

    def test_relaxed_requires_family_b(self):
        with pytest.raises(QueryError) as exc:
            Query.parse({"kind": "relaxed"})
        assert exc.value.code == "invalid_query"

    def test_bad_max_k_typed(self):
        with pytest.raises(QueryError):
            Query.parse({"kind": "whatif", "max_k": 0})
        with pytest.raises(QueryError):
            Query.parse({"kind": "whatif", "max_k": True})

    def test_unknown_metric_typed(self):
        with pytest.raises(QueryError) as exc:
            Query.parse({"kind": "analytics", "metric": "nope"})
        assert exc.value.code == "unknown_query"

    def test_fingerprints_never_cross_kinds(self):
        fa, fb = synth.two_family_preset(core=4, watchers=0)
        fps = {
            Query.parse({"kind": "whatif", "max_k": 1}).fingerprint(),
            Query.parse({"kind": "whatif", "max_k": 2}).fingerprint(),
            Query.parse({"kind": "relaxed", "family_b": fb}).fingerprint(),
            Query.parse({"kind": "analytics",
                         "metric": "pagerank"}).fingerprint(),
            Query.parse({"kind": "analytics",
                         "metric": "top_tier"}).fingerprint(),
            "",  # intersection
        }
        assert len(fps) == 6  # all distinct, intersection empty

    def test_wire_roundtrip(self):
        fa, fb = synth.two_family_preset(core=4, watchers=0)
        for raw in (
            {"kind": "relaxed", "family_b": fb},
            {"kind": "whatif", "max_k": 2, "candidates": ["TFC0000"]},
            {"kind": "analytics", "metric": "splitting_set",
             "splitting_max_k": 1},
        ):
            q = Query.parse(raw)
            assert Query.parse(roundtrip(q.to_wire())) == q


# ---------------------------------------------------------------------------
# relaxed two-family mode


class TestRelaxedDifferential:
    @pytest.mark.parametrize("broken", [False, True])
    def test_preset_vs_subset_oracle(self, rec, broken):
        fa, fb = synth.two_family_preset(
            core=8, watchers=3, broken=broken, seed=7,
        )
        out = QueryEngine(backend="python").resolve(
            fa, Query.parse({"kind": "relaxed", "family_b": fb})
        )
        assert out.verdict == subset_oracle(fa, fb)
        assert out.verdict == (not broken)
        check_certificate(roundtrip(out.cert), fa)
        if not out.verdict:
            wit = out.result["witness"]
            assert not set(wit["family_a"]) & set(wit["family_b"])

    @pytest.mark.parametrize("fixture,verdict", FIXTURE_PAIRS)
    def test_self_family_matches_intersection(self, rec, fixture, verdict):
        """relaxed(A, A) degenerates to the single-family question: the
        verdict must equal the one-shot pipeline's on both fixture
        pairs (the trivial pair also brute-forced by the subset
        oracle)."""
        nodes = fixture_nodes(fixture)
        out = QueryEngine(backend="python").resolve(
            nodes, Query.parse({"kind": "relaxed", "family_b": nodes})
        )
        assert out.verdict is verdict
        check_certificate(roundtrip(out.cert), nodes)
        if "trivial" in fixture:
            assert out.verdict == subset_oracle(nodes, nodes)

    def test_vectorized_matches_host_oracle(self, rec):
        """The circuit-vectorized search and the stdlib semantics oracle
        agree window-for-window: same verdict, same first-witness
        A-quorum, same enumeration count."""
        for broken in (False, True):
            fa, fb = synth.two_family_preset(
                core=7, watchers=2, broken=broken, seed=11,
            )
            ga = build_graph(parse_fbas(fa))
            gb = build_graph(parse_fbas(fb))
            (_sid, members), = quorum_bearing_sccs(ga)
            qa_v, qb_v, enum_v, _engine = _relaxed_search(ga, gb, members)
            qa_h, qb_h, enum_h = relaxed_disjoint_witness(ga, gb, members)
            assert (qa_v is None) == (qa_h is None)
            assert enum_v == enum_h
            assert qa_v == qa_h
            if qb_v is not None:
                # The fast scoped guard may return a smaller B-quorum
                # than the host's whole-graph greatest fixpoint; both
                # must be real B-quorums disjoint from qa.
                assert not set(qa_v) & set(qb_v)
                assert cross_family_disjoint_quorum(gb, qa_v)

    def test_two_circuit_restriction_parity(self, rec):
        """restrict_two_family's scoped circuits agree with the host
        semantics on both families for every singleton-and-pair window
        of the SCC."""
        import numpy as np

        fa, fb = synth.two_family_preset(core=6, watchers=2, seed=3)
        ga = build_graph(parse_fbas(fa))
        gb = build_graph(parse_fbas(fb))
        (_sid, members), = quorum_bearing_sccs(ga)
        a_scoped, b_scoped, _ = restrict_two_family(
            encode_circuit(ga), encode_circuit(gb), members
        )
        m = len(members)
        masks = np.zeros((m * m, m), dtype=bool)
        k = 0
        for i in range(m):
            for j in range(m):
                masks[k, i] = True
                masks[k, j] = True
                k += 1
        for circ, graph in ((a_scoped, ga), (b_scoped, gb)):
            fix = max_quorum_np(circ, masks)
            for row, mask in zip(fix, masks):
                chosen = [members[i] for i in range(m) if mask[i]]
                avail = [False] * graph.n
                for v in chosen:
                    avail[v] = True
                host = max_quorum(graph, chosen, avail)
                assert sorted(members[i] for i in range(m) if row[i]) \
                    == sorted(host)

    def test_mismatched_node_set_typed(self, rec):
        fa, _fb = synth.two_family_preset(core=4, watchers=0)
        other = synth.majority_fbas(4, prefix="OTHER")
        with pytest.raises(QueryError) as exc:
            QueryEngine(backend="python").resolve(
                fa, Query.parse({"kind": "relaxed", "family_b": other})
            )
        assert exc.value.code == "invalid_query"

    def test_forged_relaxed_witness_rejected(self, rec):
        fa, fb = synth.two_family_preset(
            core=8, watchers=3, broken=True, seed=7,
        )
        out = QueryEngine(backend="python").resolve(
            fa, Query.parse({"kind": "relaxed", "family_b": fb})
        )
        bad = roundtrip(out.cert)
        bad["witness"]["q2"] = bad["witness"]["q1"]
        with pytest.raises(CheckFailure):
            check_certificate(bad, fa)
        short = roundtrip(out.cert)
        short["verdict"] = True
        short["coverage"] = {"sccs": [{
            "size": 8, "window_space": 255, "windows_enumerated": 100,
            "nodes": [],
        }]}
        del short["witness"]
        with pytest.raises(CheckFailure):
            check_certificate(short, fa)


# ---------------------------------------------------------------------------
# whatif removal sweeps


class TestWhatif:
    def test_table_vs_sequential_oracle(self, rec):
        """Every frontier row's verdict equals a from-scratch solve of
        the masked variant — the stdlib parity bar."""
        base = synth.majority_fbas(5, prefix="WIF")
        out = QueryEngine(backend="python").resolve(
            base, Query.parse({"kind": "whatif", "max_k": 3})
        )
        assert out.result["table"][0]["removed"] == []
        for row in out.result["table"]:
            expect = solve(
                mask_nodes(base, row["removed"]), backend="python"
            ).intersects
            assert row["verdict"] is expect
        # 3-of-5 majority: any 3 departures silence every quorum.
        assert out.verdict is False
        assert len(out.result["minimal_failing"]) == 3
        check_certificate(roundtrip(out.cert), base)
        check_certificate(
            roundtrip(out.result["failing_cert"]),
            mask_nodes(base, out.result["minimal_failing"]),
        )

    def test_packed_vs_sequential_byte_parity(self, rec):
        """The acceptance bar: the whatif verdict table is byte-identical
        between the lane-packed batch and the never-packed sequential
        path (same variants, same masks, same sweep backend)."""
        base = synth.majority_fbas(6, prefix="WIP")
        q = Query.parse({"kind": "whatif", "max_k": 2})
        tables = {}
        for label, pack in (("packed", True), ("sequential", False)):
            out = QueryEngine(
                backend=TpuSweepBackend(batch=256), pack=pack,
            ).resolve(base, q)
            tables[label] = json.dumps(
                {"table": out.result["table"],
                 "minimal_failing": out.result["minimal_failing"],
                 "verdict": out.verdict},
                sort_keys=True,
            )
        assert tables["packed"] == tables["sequential"]

    def test_unknown_candidate_typed(self, rec):
        base = synth.majority_fbas(5, prefix="WIF")
        with pytest.raises(QueryError) as exc:
            QueryEngine(backend="python").resolve(
                base,
                Query.parse({"kind": "whatif", "candidates": ["GHOST"]}),
            )
        assert exc.value.code == "invalid_query"

    def test_frontier_truncation_is_loud(self, rec):
        base = synth.majority_fbas(9, prefix="WIT")
        out = QueryEngine(backend="python", whatif_limit=5).resolve(
            base, Query.parse({"kind": "whatif", "max_k": 2})
        )
        assert out.result["truncated"] is True
        assert out.result["variants"] == 5

    def test_delta_reuse_across_frontier_steps(self, rec):
        """Acceptance bar: watcher-only removals leave the core SCC's
        fingerprint untouched, so a k-frontier step through a
        delta-enabled serve engine composes the core fragment from the
        store — delta_scc_reuse_pct > 0 across the step."""
        base = synth.stellar_like_fbas(
            n_core_orgs=3, per_org=2, n_watchers=6, n_null=1,
            n_dangling=0, seed=5,
        )
        watchers = sorted(
            n["publicKey"] for n in base
            if str(n.get("publicKey", "")).startswith("WATCH")
        )[:3]
        with _engine(ServeEngine(backend="python")) as eng:
            t1 = eng.submit(base, query={
                "kind": "whatif", "candidates": watchers, "max_k": 1,
            })
            assert t1.result(60.0).intersects is True
            t2 = eng.submit(base, query={
                "kind": "whatif", "candidates": watchers, "max_k": 2,
            })
            assert t2.result(60.0).intersects is True
        counters, gauges = rec.snapshot()
        assert counters.get("delta.scc_hits", 0) > 0
        assert gauges.get("delta.scc_reuse_pct", 0.0) > 0.0


# ---------------------------------------------------------------------------
# analytics queries


class TestAnalyticsQueries:
    def test_top_tier_matches_module(self, rec):
        from quorum_intersection_tpu.analytics.top_tier import top_tier

        nodes = fixture_nodes("nested_correct")
        graph = build_graph(parse_fbas(nodes))
        expect = []
        for _sid, scc in quorum_bearing_sccs(graph):
            part, _count = top_tier(graph, scc)
            expect.extend(graph.node_ids[v] for v in part)
        out = QueryEngine(backend="python").resolve(
            nodes, Query.parse({"kind": "analytics", "metric": "top_tier"})
        )
        assert out.verdict is True
        assert out.result["members"] == sorted(expect)
        check_certificate(roundtrip(out.cert), nodes)

    def test_blocking_set_matches_module_and_reproves(self, rec):
        from quorum_intersection_tpu.analytics.resilience import (
            minimal_blocking_set,
        )

        base = synth.majority_fbas(7, prefix="ABQ")
        graph = build_graph(parse_fbas(base))
        expect = []
        for _sid, scc in quorum_bearing_sccs(graph):
            expect.extend(
                graph.node_ids[v] for v in minimal_blocking_set(graph, scc)
            )
        out = QueryEngine(backend="python").resolve(
            base,
            Query.parse({"kind": "analytics", "metric": "blocking_set"}),
        )
        assert out.result["blocking"] == sorted(expect)
        notes = check_certificate(roundtrip(out.cert), base)
        assert any("blocking-halts" in n for n in notes)

    def test_splitting_set_matches_module_and_reproves(self, rec):
        from quorum_intersection_tpu.analytics.splitting import (
            minimum_splitting_set,
        )

        base = synth.majority_fbas(5, prefix="ASQ")
        expect = minimum_splitting_set(base, max_k=2)
        out = QueryEngine(backend="python").resolve(
            base,
            Query.parse({"kind": "analytics", "metric": "splitting_set",
                         "splitting_max_k": 2}),
        )
        assert out.result["splitting"] == expect
        notes = check_certificate(roundtrip(out.cert), base)
        assert any("splitting-witness" in n for n in notes)

    def test_forged_blocking_proof_rejected(self, rec):
        base = synth.majority_fbas(7, prefix="ABF")
        out = QueryEngine(backend="python").resolve(
            base,
            Query.parse({"kind": "analytics", "metric": "blocking_set"}),
        )
        bad = roundtrip(out.cert)
        # Swap the proof's node list for a DIFFERENT (unmasked) network:
        # the checker must re-derive the mask and refuse.
        bad["proof"]["nodes"] = base
        with pytest.raises(CheckFailure):
            check_certificate(bad, base)

    def test_forged_splitting_proof_rejected(self, rec):
        base = synth.majority_fbas(5, prefix="ASF")
        out = QueryEngine(backend="python").resolve(
            base,
            Query.parse({"kind": "analytics", "metric": "splitting_set",
                         "splitting_max_k": 2}),
        )
        bad = roundtrip(out.cert)
        # Swap the proof's reduced network for a DIFFERENT genuinely
        # split network of the right size: the checker must re-derive
        # the byzantine deletion from the primary and refuse.
        forged = synth.majority_fbas(
            len(bad["proof"]["nodes"]), broken=True, prefix="FRG",
        )
        bad["proof"]["nodes"] = forged
        with pytest.raises(CheckFailure):
            check_certificate(bad, base)

    def test_pagerank_matches_module(self, rec):
        from quorum_intersection_tpu.analytics.pagerank import pagerank_auto

        nodes = fixture_nodes("trivial_correct")
        graph = build_graph(parse_fbas(nodes))
        ranks, _engine_name = pagerank_auto(graph)
        out = QueryEngine(backend="python").resolve(
            nodes, Query.parse({"kind": "analytics", "metric": "pagerank"})
        )
        got = dict((k, v) for k, v in out.result["ranks"])
        for v in range(graph.n):
            assert got[graph.node_ids[v]] == pytest.approx(
                float(ranks[v]), abs=1e-6
            )

    def test_splitting_pool_overbudget_typed(self, rec):
        base = synth.majority_fbas(24, prefix="POOL")
        with pytest.raises(QueryError) as exc:
            QueryEngine(backend="python").resolve(
                base,
                Query.parse({"kind": "analytics",
                             "metric": "splitting_set"}),
            )
        assert exc.value.code == "query_overbudget"


# ---------------------------------------------------------------------------
# fault degrade


class TestDispatchFault:
    def test_fault_degrades_typed_never_wrong(self, rec):
        fa, fb = synth.two_family_preset(core=6, watchers=0, seed=1)
        eng = QueryEngine(backend="python")
        q = Query.parse({"kind": "relaxed", "family_b": fb})
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule(point="query.dispatch", mode="error",
                             first=1, every=False),
        ]))
        with pytest.raises(QueryError) as exc:
            eng.resolve(fa, q)
        assert exc.value.code == "query_degraded"
        # Second resolution (the rule fired exactly once): full verdict.
        out = eng.resolve(fa, q)
        assert out.verdict is True
        counters, _ = rec.snapshot()
        assert counters.get("query.errors", 0) == 1

    def test_cancel_token_stops_relaxed_and_analytics(self, rec):
        """The serve deadline supervisor's CancelToken is honored inside
        the relaxed chunk loop and the analytics SCC loops — a tripped
        token raises SearchCancelled instead of holding the drain
        thread through the whole enumeration."""
        from quorum_intersection_tpu.backends.base import (
            CancelToken,
            SearchCancelled,
        )

        fa, fb = synth.two_family_preset(core=8, watchers=2, seed=6)
        cancel = CancelToken()
        cancel.cancel()
        eng = QueryEngine(backend="python")
        with pytest.raises(SearchCancelled):
            eng.resolve(fa, Query.parse({"kind": "relaxed",
                                         "family_b": fb}), cancel=cancel)
        with pytest.raises(SearchCancelled):
            eng.resolve(fa, Query.parse({"kind": "analytics",
                                         "metric": "top_tier"}),
                        cancel=cancel)

    def test_intersection_path_never_routes_through_dispatch(self, rec):
        base = synth.majority_fbas(5, prefix="FLT")
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule(point="query.dispatch", mode="error"),
        ]))
        out = QueryEngine(backend="python").resolve(base, Query.parse(None))
        assert out.verdict is True  # every-hit rule, yet untouched

    def test_served_query_fault_is_typed_error_line(self, rec):
        base = synth.majority_fbas(5, prefix="FSV")
        faults.install_plan(faults.FaultPlan([
            faults.FaultRule(point="query.dispatch", mode="error",
                             first=1, every=False),
        ]))
        with _engine(ServeEngine(backend="python")) as eng:
            t = eng.submit(base, query={"kind": "analytics",
                                        "metric": "pagerank"})
            with pytest.raises(QueryError):
                t.result(60.0)
            # The legacy path keeps serving while queries degrade.
            assert eng.submit(base).result(60.0).intersects is True


# ---------------------------------------------------------------------------
# serve / fleet round-trips


class _engine:
    def __init__(self, engine):
        self.engine = engine

    def __enter__(self):
        self.engine.start()
        return self.engine

    def __exit__(self, *exc):
        self.engine.stop(drain=True, timeout=30.0)
        return False


def _mixed_stream():
    base = synth.majority_fbas(7, prefix="MIX")
    fa, fb = synth.two_family_preset(core=8, watchers=3, broken=True, seed=2)
    fa2, fb2 = synth.two_family_preset(core=8, watchers=3, seed=2)
    return [
        (base, None),
        (base, {"kind": "whatif", "max_k": 1}),
        (fa, {"kind": "relaxed", "family_b": fb}),
        (fa2, {"kind": "relaxed", "family_b": fb2}),
        (base, {"kind": "analytics", "metric": "top_tier"}),
        (base, {"kind": "analytics", "metric": "blocking_set"}),
    ]


def _oracle_verdicts(stream):
    oracle = QueryEngine(backend="python")
    return [
        oracle.resolve(nodes, Query.parse(raw)).verdict
        for nodes, raw in stream
    ]


class TestServeFleetRoundTrip:
    def test_serve_mixed_stream(self, rec):
        stream = _mixed_stream()
        expected = _oracle_verdicts(stream)
        with _engine(ServeEngine(backend="python")) as eng:
            tickets = [
                eng.submit(nodes, query=raw) for nodes, raw in stream
            ]
            responses = [t.result(120.0) for t in tickets]
        for (nodes, raw), resp, expect in zip(stream, responses, expected):
            assert resp.intersects is expect
            if raw is None:
                assert resp.result is None
            else:
                assert resp.result["kind"] == raw["kind"]
                assert resp.cert is not None
                if raw["kind"] == "relaxed":
                    check_certificate(roundtrip(resp.cert), nodes)

    def test_fleet_mixed_stream(self, rec, tmp_path):
        stream = _mixed_stream()
        expected = _oracle_verdicts(stream)
        fleet = FleetEngine(
            2, backend="python", worker_mode="local",
            journal_dir=tmp_path / "flt", probe_interval_s=60.0,
        )
        fleet.start()
        try:
            tickets = [
                fleet.submit(nodes, query=raw) for nodes, raw in stream
            ]
            responses = [t.result(120.0) for t in tickets]
        finally:
            fleet.stop(drain=True, timeout=60.0)
        for (nodes, raw), resp, expect in zip(stream, responses, expected):
            assert resp.intersects is expect
            if raw is not None:
                # The worker's result payload and certificate relay
                # through the front door intact, checker-valid.
                assert resp.result["kind"] == raw["kind"]
                assert resp.cert is not None
                if raw["kind"] == "relaxed":
                    check_certificate(roundtrip(resp.cert), nodes)

    def test_query_journal_replay(self, rec, tmp_path):
        """A journaled-but-unanswered typed query replays on restart and
        re-resolves the SAME question (the extended fingerprint pins
        it to its kind)."""
        fa, fb = synth.two_family_preset(
            core=8, watchers=3, broken=True, seed=4,
        )
        raw = {"kind": "relaxed", "family_b": fb}
        q = Query.parse(raw)
        fp = snapshot_fingerprint(build_graph(parse_fbas(fa)))
        fp = f"{fp}:q:{q.fingerprint()}"
        path = tmp_path / "q.journal"
        journal = RequestJournal(path)
        journal.append_request("qr-1", fp, fa, None, query=q.to_wire())
        journal.close()
        with _engine(ServeEngine(backend="python", journal=path)) as eng:
            report = eng._replay_report
            assert report["verdicts"] == {"qr-1": False}
            # The replayed result is cached under the extended key: an
            # identical relaxed query is a hit, a bare intersection on
            # the same snapshot is NOT.
            hit = eng.submit(fa, query=raw).result(60.0)
            assert hit.cached is True and hit.intersects is False
            miss = eng.submit(fa).result(60.0)
            assert miss.cached is False and miss.intersects is True

    def test_query_journal_bad_query_quarantined(self, rec, tmp_path):
        base = synth.majority_fbas(5, prefix="QJQ")
        fp = snapshot_fingerprint(build_graph(parse_fbas(base)))
        path = tmp_path / "bad.journal"
        journal = RequestJournal(path)
        journal.append_request("qr-bad", fp, base, None,
                               query={"kind": "bogus"})
        journal.close()
        with _engine(ServeEngine(backend="python", journal=path)) as eng:
            report = eng._replay_report
        assert report["verdicts"] == {}
        assert report["quarantined"] == 1


# ---------------------------------------------------------------------------
# synth scale presets


class TestSynthPresets:
    def test_nested_hierarchy_deterministic(self):
        a = synth.nested_hierarchy(400, seed=3)
        b = synth.nested_hierarchy(400, seed=3)
        c = synth.nested_hierarchy(400, seed=4)
        assert json.dumps(a) == json.dumps(b)
        assert json.dumps(a) != json.dumps(c)
        assert len(a) == 400

    def test_nested_hierarchy_10k_generates(self):
        nodes = synth.nested_hierarchy(10_000, seed=0)
        assert len(nodes) == 10_000
        # Deterministic and JSON-serializable (the serving layer
        # journals exactly these dicts).
        json.dumps(nodes[-1])

    def test_nested_hierarchy_verdict_pair(self, rec):
        correct = synth.nested_hierarchy(60, seed=1)
        broken = synth.nested_hierarchy(60, seed=1, broken=True)
        assert solve(correct, backend="python").intersects is True
        assert solve(broken, backend="python").intersects is False

    def test_two_family_preset_deterministic(self):
        a = synth.two_family_preset(core=8, watchers=4, seed=5)
        b = synth.two_family_preset(core=8, watchers=4, seed=5)
        assert json.dumps(a) == json.dumps(b)

    def test_two_family_broken_invisible_to_family_a(self, rec):
        """The adversarial point: the broken twin's cross-family split is
        invisible to family A's own single-family verdict."""
        fa, fb = synth.two_family_preset(
            core=9, watchers=3, broken=True, seed=0,
        )
        assert solve(fa, backend="python").intersects is True
        out = QueryEngine(backend="python").resolve(
            fa, Query.parse({"kind": "relaxed", "family_b": fb})
        )
        assert out.verdict is False


# ---------------------------------------------------------------------------
# fleet respawn + shared-store GC satellites


class TestFleetRespawn:
    def test_respawn_restores_ring_and_serves(self, rec, tmp_path):
        base = synth.majority_fbas(7, prefix="RSP")
        fleet = FleetEngine(
            2, backend="python", worker_mode="local",
            journal_dir=tmp_path / "rsp", probe_interval_s=60.0,
        )
        fleet.start()
        try:
            fleet.kill_worker(fleet.worker_ids()[0], evict=True)
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                counters, _ = rec.snapshot()
                if counters.get("fleet.respawns", 0) >= 1:
                    break
                time.sleep(0.05)
            counters, gauges = rec.snapshot()
            assert counters.get("fleet.respawns", 0) == 1
            assert len(fleet.worker_ids()) == 2
            assert gauges.get("fleet.ring_size") == 2
            assert fleet.submit(base).result(60.0).intersects is True
        finally:
            fleet.stop(drain=True, timeout=60.0)

    def test_respawn_disabled_keeps_shrunken_ring(self, rec, tmp_path):
        fleet = FleetEngine(
            2, backend="python", worker_mode="local",
            journal_dir=tmp_path / "off", probe_interval_s=60.0,
            respawn_max=0,
        )
        fleet.start()
        try:
            fleet.kill_worker(fleet.worker_ids()[0], evict=True)
            time.sleep(0.5)
            counters, _ = rec.snapshot()
            assert counters.get("fleet.respawns", 0) == 0
            assert len(fleet.worker_ids()) == 1
        finally:
            fleet.stop(drain=True, timeout=60.0)

    def test_respawn_bounded_per_slot(self, rec, tmp_path):
        fleet = FleetEngine(
            2, backend="python", worker_mode="local",
            journal_dir=tmp_path / "bnd", probe_interval_s=60.0,
            respawn_max=1,
        )
        fleet.start()
        try:
            slot = fleet.worker_ids()[0]
            fleet.kill_worker(slot, evict=True)
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                if f"{slot}.r1" in fleet.worker_ids():
                    break
                time.sleep(0.05)
            assert f"{slot}.r1" in fleet.worker_ids()
            fleet.kill_worker(f"{slot}.r1", evict=True)
            time.sleep(0.6)
            counters, _ = rec.snapshot()
            assert counters.get("fleet.respawns", 0) == 1  # budget spent
            assert len(fleet.worker_ids()) == 1
        finally:
            fleet.stop(drain=True, timeout=60.0)


class TestSharedStoreGC:
    def test_gc_sweeps_lru_by_mtime(self, rec):
        with tempfile.TemporaryDirectory() as tmp:
            store = SharedSccStore(tmp, max_mb=0.001)  # ~1 KiB budget
            for i in range(20):
                assert store.put(
                    "scan", f"fp{i:03d}",
                    {"quorum_local": list(range(40))},
                )
            counters, _ = rec.snapshot()
            assert counters.get("delta.store_evictions", 0) > 0
            # The stalest fragments went first; the newest survives and
            # an evicted one is a plain miss.
            assert store.get("scan", "fp019") is not None
            assert store.get("scan", "fp000") is None

    def test_gc_disabled_by_default(self, rec):
        with tempfile.TemporaryDirectory() as tmp:
            store = SharedSccStore(tmp)
            for i in range(20):
                store.put("scan", f"fp{i:03d}",
                          {"quorum_local": list(range(40))})
            assert store.get("scan", "fp000") is not None
            counters, _ = rec.snapshot()
            assert counters.get("delta.store_evictions", 0) == 0
