"""Trust graph + Tarjan SCC tests, including dangling-ref policies (Q1) and
parallel-edge multiplicity (Q7)."""

import pytest

from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import majority_fbas


def _parse(data):
    return parse_fbas(data)


def test_edges_with_multiplicity_and_depth():
    fbas = _parse(
        [
            {
                "publicKey": "A",
                "quorumSet": {
                    "threshold": 1,
                    "validators": ["B", "B"],
                    "innerQuorumSets": [{"threshold": 1, "validators": ["B", "A"]}],
                },
            },
            {"publicKey": "B", "quorumSet": None},
        ]
    )
    g = build_graph(fbas)
    # One edge per occurrence at every depth (cpp:455-464): B×3 plus self-loop A.
    assert sorted(g.succ[0]) == [0, 1, 1, 1]
    assert g.succ[1] == []
    assert g.n_edges == 4
    assert g.in_degrees() == [1, 3]


def test_dangling_strict_drops_and_counts():
    fbas = _parse(
        [
            {"publicKey": "A", "quorumSet": {"threshold": 2, "validators": ["B", "GHOST"]}},
            {"publicKey": "B", "quorumSet": None},
        ]
    )
    g = build_graph(fbas, dangling="strict")
    assert g.dangling_refs == 1
    assert g.qsets[0].members == (1,)
    assert g.qsets[0].n_dangling == 1
    assert g.qsets[0].threshold == 2  # threshold untouched: dropped ≡ never-available
    assert g.succ[0] == [1]


def test_dangling_alias0_reproduces_reference_bug():
    fbas = _parse(
        [
            {"publicKey": "A", "quorumSet": {"threshold": 2, "validators": ["B", "GHOST"]}},
            {"publicKey": "B", "quorumSet": None},
        ]
    )
    g = build_graph(fbas, dangling="alias0")
    assert g.qsets[0].members == (1, 0)  # GHOST aliases to vertex 0 (Q1, cpp:456)
    assert sorted(g.succ[0]) == [0, 1]


def test_bad_policy_rejected():
    fbas = _parse([{"publicKey": "A", "quorumSet": None}])
    with pytest.raises(ValueError):
        build_graph(fbas, dangling="nope")


def test_tarjan_simple_cycle_plus_tail():
    # 0↔1 cycle, 2→0 tail: two SCCs; the cycle is the sink → component 0.
    n, succ = 3, [[1], [0], [0]]
    count, comp = tarjan_scc(n, succ)
    assert count == 2
    assert comp[0] == comp[1] == 0  # sink SCC numbered first (reverse topo)
    assert comp[2] == 1
    assert group_sccs(n, comp, count) == [[0, 1], [2]]


def test_tarjan_self_loop_and_isolated():
    n, succ = 3, [[0], [], [1]]
    count, comp = tarjan_scc(n, succ)
    assert count == 3
    assert len(set(comp)) == 3


def test_tarjan_reverse_topological_numbering():
    # Chain of singleton SCCs 0→1→2→3: sink (3) must get the lowest id.
    count, comp = tarjan_scc(4, [[1], [2], [3], []])
    assert count == 4
    assert comp[3] < comp[2] < comp[1] < comp[0]


def test_majority_fbas_single_scc():
    fbas = _parse(majority_fbas(8))
    g = build_graph(fbas)
    count, comp = tarjan_scc(g.n, g.succ)
    assert count == 1


def test_reference_fixture_scc_and_dangling_counts(ref_fixture):
    """SCC and dangling-ref counts match SURVEY.md §4.1/§2.3-Q1 [verified].

    The survey's 7/9 dangling figures are *distinct* unknown IDs; occurrence
    counts (every appearance at every depth) are 16/22.  SCC counts depend on
    the dangling policy: alias0 adds trust edges into vertex 0 (Q1), which
    merges one SCC in broken.json (53 vs strict's 54).  The reference numbers
    (49/53) are the alias0 semantics; verdicts agree under both policies.
    """
    expectations = {
        # name: (dangling occurrences, distinct, sccs_strict, sccs_alias0)
        "correct.json": (16, 7, 49, 49),
        "broken.json": (22, 9, 54, 53),
    }
    from quorum_intersection_tpu.fbas.sanitize import dangling_refs
    import json

    for name, (n_occ, n_distinct, sccs_strict, sccs_alias0) in expectations.items():
        path = ref_fixture(name)
        with open(path) as f:
            raw = f.read()
        assert len(dangling_refs(json.loads(raw))) == n_distinct
        fbas = _parse(raw)
        for policy, expected in (("strict", sccs_strict), ("alias0", sccs_alias0)):
            g = build_graph(fbas, dangling=policy)
            assert g.dangling_refs == n_occ
            count, _ = tarjan_scc(g.n, g.succ)
            assert count == expected
