"""qi-trace (ISSUE 6 tentpole): trace-context propagation across process
boundaries, the Chrome/Perfetto trace-event exporter, the crash flight
recorder (ring + crash-only dump + its own fault point), and the live
/healthz + /metrics endpoint — plus the legacy ``--timing`` byte-compat
guarantee with tracing enabled."""

import json
import subprocess
import sys
import threading
import urllib.request

import pytest

from quorum_intersection_tpu.fbas.synth import majority_fbas
from quorum_intersection_tpu.utils import telemetry
from quorum_intersection_tpu.utils.telemetry import (
    ChromeTraceSink,
    FLIGHT_RECORDER_N,
    RunRecord,
    TraceContext,
    dump_flight_recorder,
)

CLI = [sys.executable, "-m", "quorum_intersection_tpu"]


def _env(**extra):
    import os

    env = dict(os.environ)
    env.update(extra)
    return env


@pytest.fixture
def fresh_record():
    rec = telemetry.reset_run_record()
    yield rec
    telemetry.reset_run_record()


def load_trace(path):
    """Load a trace-event file the way Perfetto does: the enclosing array
    is deliberately unterminated (crash tolerance), so close it here."""
    text = path.read_text().strip()
    if text.endswith(","):
        text = text[:-1]
    if not text.endswith("]"):
        text += "]"
    return json.loads(text)


class TestTraceContext:
    def test_env_round_trip(self):
        ctx = TraceContext("abcd1234", span_id=7, pid=4711)
        assert TraceContext.from_env(ctx.to_env()) == ctx

    def test_from_env_blank_and_malformed(self):
        assert TraceContext.from_env("") is None
        assert TraceContext.from_env("   ") is None
        # A garbled tail costs linkage, never a run.
        ctx = TraceContext.from_env("abc:not-a-number:nope")
        assert ctx is not None and ctx.trace_id == "abc"
        assert ctx.span_id is None and ctx.pid is None

    def test_record_mints_unique_ids(self):
        a, b = RunRecord(), RunRecord()
        assert a.trace_id and b.trace_id and a.trace_id != b.trace_id

    def test_record_inherits_from_env(self, monkeypatch):
        monkeypatch.setenv("QI_TRACE_CONTEXT", "feedf00d12345678:9:123")
        rec = RunRecord()
        assert rec.trace_id == "feedf00d12345678"
        assert rec.parent_ctx.span_id == 9
        assert rec.parent_ctx.pid == 123

    def test_spans_and_events_stamped(self, fresh_record):
        rec = fresh_record
        with rec.span("s"):
            rec.event("e")
        assert rec.spans[0].trace_id == rec.trace_id
        assert rec.spans[0].pid == rec.pid and rec.spans[0].tid > 0
        assert rec.events[0]["trace_id"] == rec.trace_id

    def test_child_process_adopts_trace_id(self, tmp_path):
        # The cross-PROCESS half of the propagation contract: a CLI child
        # handed QI_TRACE_CONTEXT joins the parent's trace and records the
        # parent span/pid in its meta line.
        stream = tmp_path / "child.jsonl"
        ctx = TraceContext("cafe0123deadbeef", span_id=42, pid=1000)
        proc = subprocess.run(
            CLI + ["--backend", "python"],
            input=json.dumps(majority_fbas(3)),
            capture_output=True, text=True, timeout=120,
            env=_env(QI_TRACE_CONTEXT=ctx.to_env(),
                     QI_METRICS_JSON=str(stream)),
        )
        assert proc.returncode == 0, proc.stderr
        lines = [json.loads(l) for l in stream.read_text().splitlines()]
        meta = next(l for l in lines if l["kind"] == "meta")
        assert meta["trace_id"] == "cafe0123deadbeef"
        assert meta["parent_span"] == 42 and meta["parent_pid"] == 1000
        span_ids = {l["trace_id"] for l in lines if l["kind"] == "span"}
        assert span_ids == {"cafe0123deadbeef"}


class TestChromeTraceExporter:
    def test_sink_converts_all_kinds(self, tmp_path, fresh_record):
        path = tmp_path / "t.json"
        rec = fresh_record
        rec.add_sink(ChromeTraceSink(str(path)))
        with rec.span("outer", scc=5):
            rec.event("mark", x=1)
        rec.finish()
        events = load_trace(path)
        phases = {e["ph"] for e in events}
        assert {"M", "X", "i"} <= phases
        x = next(e for e in events if e["ph"] == "X")
        assert x["name"] == "outer" and x["dur"] >= 1.0
        assert x["args"] == {"scc": 5}
        assert isinstance(x["pid"], int) and isinstance(x["tid"], int)

    def test_cli_trace_out_one_timeline(self, tmp_path):
        # Acceptance: one CLI run with --trace-out produces a loadable
        # trace in which the race winner, race loser, ladder rungs, the
        # native call, and the routing appear as spans of ONE process
        # timeline (the single-trace_id half is pinned via the JSONL
        # stream, whose span lines all carry trace_id).
        trace = tmp_path / "t.json"
        stream = tmp_path / "m.jsonl"
        proc = subprocess.run(
            CLI + ["--trace-out", str(trace), "--metrics-json", str(stream)],
            input=json.dumps(majority_fbas(9)),
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        events = load_trace(trace)
        names = {e["name"] for e in events if e["ph"] == "X"}
        assert {"route", "race", "race.oracle", "race.sweep",
                "ladder.rung"} <= names, names
        assert any(e["ph"] == "M" and e["name"] == "process_name"
                   for e in events)
        lines = [json.loads(l) for l in stream.read_text().splitlines()]
        trace_ids = {
            l["trace_id"] for l in lines if l["kind"] in ("span", "event")
        }
        assert len(trace_ids) == 1

    def test_packed_sweep_spans_share_trace(self, fresh_record):
        # Acceptance: per-pack sweep spans (and their window events) carry
        # the same trace_id as everything else in the run.
        from quorum_intersection_tpu.pipeline import check_many

        rec = fresh_record
        res = check_many(
            [majority_fbas(7), majority_fbas(9)], backend="tpu-sweep"
        )
        assert [r.intersects for r in res] == [True, True]
        names = {sp.name for sp in rec.spans}
        assert {"sweep.pack", "pipeline.check_many"} <= names, names
        assert {sp.trace_id for sp in rec.spans} == {rec.trace_id}
        assert rec.gauges.get("sweep.packs_in_flight") == 0

    def test_env_hook_attaches_sink(self, tmp_path):
        trace = tmp_path / "envt.json"
        proc = subprocess.run(
            CLI + ["--backend", "python"],
            input=json.dumps(majority_fbas(3)),
            capture_output=True, text=True, timeout=120,
            env=_env(QI_TRACE_OUT=str(trace)),
        )
        assert proc.returncode == 0, proc.stderr
        assert any(e["ph"] == "X" for e in load_trace(trace))

    def test_timing_legacy_lines_unchanged_with_tracing(self, tmp_path):
        # Satellite acceptance: legacy --timing lines stay byte-compatible
        # (contiguous and FIRST) with the trace exporter enabled.
        proc = subprocess.run(
            CLI + ["--timing", "--backend", "python",
                   "--trace-out", str(tmp_path / "t.json")],
            input=json.dumps(majority_fbas(3)),
            capture_output=True, text=True, timeout=120,
            env=_env(QI_TRACE_OUT=str(tmp_path / "t2.json"),
                     QI_FLIGHT_RECORDER=str(tmp_path / "f.json")),
        )
        assert proc.returncode == 0
        err = proc.stderr.splitlines()
        legacy = [l for l in err if l.startswith(("[timing]", "[stats]"))]
        telem = [l for l in err if l.startswith("[telemetry]")]
        assert legacy and telem
        first_telem = err.index(telem[0])
        assert all(err.index(l) < first_telem for l in legacy)


class TestFlightRecorder:
    def test_ring_is_bounded_and_ordered(self, fresh_record):
        rec = fresh_record
        for i in range(FLIGHT_RECORDER_N + 50):
            rec.event("tick", i=i)
        tail = rec.flight_tail()
        assert len(tail) == FLIGHT_RECORDER_N
        assert tail[-1]["attrs"]["i"] == FLIGHT_RECORDER_N + 49
        assert tail[0]["attrs"]["i"] == 50  # oldest dropped first

    def test_dump_tail_matches_emitted_events(self, tmp_path, fresh_record):
        rec = fresh_record
        with rec.span("phase.search"):
            rec.event("route.decision", engine="cpp")
        out = dump_flight_recorder("test", path=str(tmp_path / "fl.json"))
        dump = json.loads((tmp_path / "fl.json").read_text())
        assert out and dump["schema"] == "qi-flight/1"
        assert dump["reason"] == "test"
        assert dump["trace_id"] == rec.trace_id
        # The dump's tail IS the emitted telemetry, line for line.
        names = [l["name"] for l in dump["tail"]]
        assert names == ["route.decision", "phase.search"]
        # Counter snapshot is taken BEFORE the dump increments it.
        assert dump["counters"].get("telemetry.dumps", 0) == 0
        assert rec.counters["telemetry.dumps"] == 1

    def test_no_path_no_dump(self, fresh_record, monkeypatch):
        monkeypatch.delenv("QI_FLIGHT_RECORDER", raising=False)
        assert dump_flight_recorder("nothing-configured") is None

    def test_seeded_fault_mid_sweep_leaves_parseable_dump(self, tmp_path):
        # Acceptance: a seeded QI_FAULTS schedule firing mid-sweep leaves a
        # flight-recorder dump whose tail matches the emitted qi-telemetry
        # events.  sweep.window=preempt on the direct sweep backend is an
        # unhandled failure — the CLI crashes (nonzero), and the dump (from
        # the fault trigger AND the unhandled-exception path) survives.
        dump_path = tmp_path / "fl.json"
        stream = tmp_path / "m.jsonl"
        proc = subprocess.run(
            CLI + ["--backend", "tpu-sweep"],
            input=json.dumps(majority_fbas(9)),
            capture_output=True, text=True, timeout=300,
            env=_env(QI_FAULTS="sweep.window=preempt@1",
                     QI_FLIGHT_RECORDER=str(dump_path),
                     QI_METRICS_JSON=str(stream)),
        )
        assert proc.returncode != 0  # the injected preempt surfaced
        dump = json.loads(dump_path.read_text())
        assert dump["schema"] == "qi-flight/1"
        assert dump["counters"]["faults.injected"] == 1
        # Tail lines cross-check against the JSONL stream byte-for-byte
        # content (the same dict went through both paths).
        stream_lines = [
            json.loads(l) for l in stream.read_text().splitlines()
        ]
        stream_events = [
            l for l in stream_lines if l["kind"] in ("span", "event")
        ]
        tail = dump["tail"]
        assert tail  # something was recorded before the crash
        assert all(line in stream_events for line in tail)
        assert any(l["name"] == "fault.injected" for l in tail)

    def test_ladder_degrade_dumps(self, tmp_path):
        # Every degrade event carries its last-N context: an injected
        # native.call error degrades native -> python (verdict unchanged)
        # and leaves a dump naming the transition.
        dump_path = tmp_path / "fl.json"
        proc = subprocess.run(
            CLI,
            input=json.dumps(majority_fbas(5)),
            capture_output=True, text=True, timeout=300,
            env=_env(QI_FAULTS="native.call=error@1+",
                     QI_FLIGHT_RECORDER=str(dump_path)),
        )
        assert proc.returncode == 0, proc.stderr  # degraded, not crashed
        assert proc.stdout.strip().endswith("true")
        dump = json.loads(dump_path.read_text())
        assert dump["reason"].startswith(("degrade:", "fault:"))
        assert dump["counters"]["ladder.degrades"] >= 1

    def test_injected_dump_fault_downgrades(self, tmp_path, fresh_record,
                                            monkeypatch):
        # The dump write is itself a declared fault point: an injected
        # disk-full OSError becomes the telemetry.dump_errors counter,
        # never a second crash (and never a file).
        from quorum_intersection_tpu.utils import faults

        monkeypatch.setenv("QI_FAULTS", "telemetry.dump=oserror@1")
        faults.clear_plan()
        try:
            target = tmp_path / "fl.json"
            out = dump_flight_recorder("downgrade-test", path=str(target))
            assert out is None
            assert not target.exists()
            rec = telemetry.get_run_record()
            assert rec.counters["telemetry.dump_errors"] == 1
            # The injected firing itself was recorded (fault.injected), and
            # its own dump attempt did not recurse.
            assert rec.counters["faults.injected"] == 1
        finally:
            monkeypatch.delenv("QI_FAULTS")
            faults.clear_plan()


class TestMetricsEndpoint:
    def _get(self, port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as resp:
            return resp.status, resp.read()

    def test_healthz_and_metrics_byte_stable_under_concurrency(
        self, fresh_record
    ):
        from quorum_intersection_tpu.utils.metrics_server import MetricsServer

        rec = fresh_record
        rec.add("ladder.degrades", 2)
        rec.gauge("ladder.rung", "tpu-sweep")
        rec.gauge("ladder.quarantined_rungs", ["native"])
        rec.gauge("sweep.packs_in_flight", 1)
        srv = MetricsServer(port=0)
        try:
            results = {"healthz": set(), "metrics": set()}
            errors = []

            def scrape():
                try:
                    for _ in range(5):
                        results["healthz"].add(
                            self._get(srv.port, "/healthz")[1]
                        )
                        results["metrics"].add(
                            self._get(srv.port, "/metrics")[1]
                        )
                except Exception as exc:  # noqa: BLE001 — surfaced below
                    errors.append(exc)

            threads = [threading.Thread(target=scrape) for _ in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert not errors
            # Byte-stable: 30 concurrent scrapes of each endpoint, one body.
            assert len(results["healthz"]) == 1
            assert len(results["metrics"]) == 1
            health = json.loads(next(iter(results["healthz"])))
            assert health["status"] == "ok"
            assert health["ladder_rung"] == "tpu-sweep"
            assert health["quarantined_rungs"] == ["native"]
            assert health["packs_in_flight"] == 1
            assert health["degrades"] == 2
            assert health["trace_id"] == rec.trace_id
            prom = next(iter(results["metrics"])).decode()
            assert "# TYPE qi_ladder_degrades counter" in prom
            assert "qi_ladder_degrades 2" in prom
        finally:
            srv.stop()

    def test_unknown_path_404(self, fresh_record):
        from quorum_intersection_tpu.utils.metrics_server import MetricsServer

        srv = MetricsServer(port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as exc_info:
                self._get(srv.port, "/nope")
            assert exc_info.value.code == 404
        finally:
            srv.stop()

    def test_env_start_and_port_conflict_is_quiet(self, monkeypatch,
                                                  fresh_record):
        from quorum_intersection_tpu.utils import metrics_server

        srv = metrics_server.MetricsServer(port=0)
        try:
            # A child inheriting the parent's port must log-and-continue.
            monkeypatch.setenv("QI_METRICS_PORT", str(srv.port))
            metrics_server.stop_server()  # clear any env-started instance
            assert metrics_server.maybe_start_from_env() is None
        finally:
            srv.stop()
            metrics_server.stop_server()

    def test_prom_endpoint_matches_textfile_encoder(self, fresh_record):
        from quorum_intersection_tpu.utils.metrics_server import MetricsServer
        from quorum_intersection_tpu.utils.telemetry import prom_lines

        rec = fresh_record
        rec.add("native.bnb_calls", 7)
        srv = MetricsServer(port=0)
        try:
            _, body = self._get(srv.port, "/metrics")
            assert body.decode() == "\n".join(prom_lines(rec)) + "\n"
        finally:
            srv.stop()
