"""Dump-scale robustness (VERDICT r2 §next-7): the full pipeline on a
~3k-node graph — the scale of a real stellarbeat `/nodes/raw` dump (the
reference's intended production input, `/root/reference/README.md:21-28`) —
with time and memory bounds asserted.

The fixture is the frozen `fixtures/dump_scale_correct.json.gz` (2 971
nodes, 21-node core SCC, 150 null qsets, 40 dangling refs); the frontier
machinery under test is exactly what grows with the dump: parse, graph
build, the native SCC scan (graph.n > NATIVE_SCAN_LIMIT), encode's O(U²)
child matrix, and the sparse O(E) PageRank path.
"""

import time
import tracemalloc

import pytest

from tests.conftest import vendored_fixture_text, vendored_manifest
from quorum_intersection_tpu.encode.circuit import encode_circuit
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.pipeline import NATIVE_SCAN_LIMIT, solve

FIXTURE = "dump_scale_correct.json.gz"


@pytest.fixture(scope="module")
def dump_graph():
    return build_graph(parse_fbas(vendored_fixture_text(FIXTURE)))


def test_full_pipeline_verdict_and_time(dump_graph):
    want = vendored_manifest()[FIXTURE]
    assert dump_graph.n == want["nodes"] >= 2900
    assert dump_graph.n > NATIVE_SCAN_LIMIT  # the native-scan regime
    t0 = time.perf_counter()
    res = solve(vendored_fixture_text(FIXTURE), backend="auto")
    seconds = time.perf_counter() - t0
    assert res.intersects is want["verdict"]
    assert res.n_sccs == want["n_sccs"]
    # Generous CI bound: the whole parse→scan→search pipeline on ~3k nodes
    # must stay interactive, not balloon exponentially with dump size (the
    # search itself only sees the 21-node core SCC).
    assert seconds < 60, f"dump-scale solve took {seconds:.1f}s"


def test_encode_memory_bounded(dump_graph):
    """encode's child matrix is O(U²) uint8 — at dump scale that must stay
    tens of MB, not GB (U ≈ nodes + inner sets)."""
    tracemalloc.start()
    circuit = encode_circuit(dump_graph)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    u = circuit.n_units
    assert u >= dump_graph.n  # one unit per node + one per inner set
    assert circuit.child.shape == (u, u)
    assert peak < 512 * 1024 * 1024, f"encode peak {peak / 1e6:.0f} MB"


def test_sparse_pagerank_path(dump_graph):
    """n > DENSE_LIMIT must route to the O(E) edge-list representation and
    converge; the dense O(N²) matrix is never materialized."""
    import numpy as np

    from quorum_intersection_tpu.analytics.pagerank import DENSE_LIMIT, pagerank_np

    assert dump_graph.n > DENSE_LIMIT
    t0 = time.perf_counter()
    ranks = pagerank_np(dump_graph)
    seconds = time.perf_counter() - t0
    assert ranks.shape == (dump_graph.n,)
    assert abs(float(ranks.sum()) - 1.0) < 1e-3
    assert np.all(ranks >= 0)
    assert seconds < 30, f"sparse PageRank took {seconds:.1f}s"


def test_cli_end_to_end(tmp_path):
    """The production entry shape: a full dump on stdin → verdict on stdout."""
    import subprocess
    import sys

    t0 = time.perf_counter()
    proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu"],
        input=vendored_fixture_text(FIXTURE),
        capture_output=True, text=True, timeout=120,
    )
    seconds = time.perf_counter() - t0
    assert proc.stdout.strip() == "true"
    assert proc.returncode == 0
    assert seconds < 90, f"dump-scale CLI took {seconds:.1f}s"
