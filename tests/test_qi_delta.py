"""qi-delta differential suite (ISSUE 9): incremental re-analysis must be
invisible in the verdicts — DeltaEngine vs from-scratch pipeline across a
long churn trace on all four backend rungs with checker-validated composed
certificates, solver-invocation counts pinning that a one-SCC diff
re-solves exactly one SCC, the SCC merge/split invalidation matrix, the
SCC-local fingerprint's identity-invariance, the closedness soundness
gate, the store's LRU bound, and the ``delta.diff`` fault degrading to the
full re-solve chain."""

import copy
import threading

import pytest

from quorum_intersection_tpu.backends.python_oracle import PythonOracleBackend
from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
from quorum_intersection_tpu.delta import DeltaEngine, SccScan, SccVerdictStore
from quorum_intersection_tpu.fbas.diff import (
    diff_snapshots,
    localize,
    project,
    scc_fingerprint,
)
from quorum_intersection_tpu.fbas.graph import (
    build_graph,
    group_sccs,
    tarjan_scc,
)
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import (
    churn_trace,
    churn_trace_steps,
    majority_fbas,
    stellar_like_fbas,
)
from quorum_intersection_tpu.pipeline import check_many, solve
from quorum_intersection_tpu.utils import faults, telemetry
from tools.check_cert import check_certificate

BACKENDS = ("python", "cpp", "tpu-sweep", "tpu-frontier")


def make_backend(name):
    if name == "tpu-sweep":
        return TpuSweepBackend(batch=512)
    if name == "tpu-frontier":
        return TpuFrontierBackend(arena=4096, pop=128)
    return name


@pytest.fixture
def rec():
    record = telemetry.reset_run_record()
    faults.clear_plan()
    yield record
    faults.clear_plan()
    telemetry.reset_run_record()


def multi_scc_base(seed=7, n_watchers=12):
    """A stellar-like snapshot: one 6-node quorum-bearing core + many
    single-node watcher SCCs — the K-SCC shape the invalidation tests
    churn one component of."""
    return stellar_like_fbas(
        n_core_orgs=3, per_org=2, n_watchers=n_watchers, seed=seed,
    )


def partition(nodes):
    graph = build_graph(parse_fbas(nodes))
    count, comp = tarjan_scc(graph.n, graph.succ)
    return graph, group_sccs(graph.n, comp, count)


def core_scc(nodes):
    """(graph, members) of the quorum-bearing core (the largest SCC in
    every multi_scc_base topology)."""
    graph, sccs = partition(nodes)
    return graph, max(sccs, key=len)


def wobble(nodes, key, delta=-1):
    """Deterministic threshold wobble on one node, by publicKey."""
    out = copy.deepcopy(nodes)
    for n in out:
        if n.get("publicKey") == key:
            q = n["quorumSet"]
            q["threshold"] = max(1, min(q["threshold"] + delta,
                                        len(q["validators"]) or 1))
            return out
    raise KeyError(key)


def core_key(nodes):
    graph, members = core_scc(nodes)
    return graph.node_ids[members[0]]


def watcher_key(nodes):
    """A churnable (non-null-qset) node OUTSIDE the core SCC."""
    graph, members = core_scc(nodes)
    core_keys = {graph.node_ids[v] for v in members}
    for n in nodes:
        q = n.get("quorumSet")
        if (n.get("publicKey") not in core_keys
                and isinstance(q, dict) and q.get("validators")):
            return n["publicKey"]
    raise AssertionError("no churnable watcher in base")


class CountingOracle:
    """Python-oracle delegate counting check_scc calls — the observable
    that pins 'a one-SCC diff re-solves exactly one SCC'."""

    name = "python"
    needs_circuit = False

    def __init__(self):
        self.calls = 0
        self._lock = threading.Lock()

    def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
        with self._lock:
            self.calls += 1
        return PythonOracleBackend().check_scc(
            graph, circuit, scc, scope_to_scc=scope_to_scc
        )


class TestSccFingerprint:
    """The SCC-local fingerprint: structural, never identity-sensitive."""

    def test_rename_invariant(self):
        base = multi_scc_base()
        g0, m0 = core_scc(base)
        renamed = copy.deepcopy(base)
        for n in renamed:
            n["name"] = (n.get("name") or "") + "~renamed"
        g1, m1 = core_scc(renamed)
        assert scc_fingerprint(g0, m0) == scc_fingerprint(g1, m1)

    def test_index_shift_invariant(self):
        """Prepending nodes shifts every global vertex index; the SCC-local
        fingerprint must not notice."""
        base = multi_scc_base()
        g0, m0 = core_scc(base)
        shifted = [
            {"publicKey": f"ZZPREF{i}", "name": f"pad{i}", "quorumSet": None}
            for i in range(3)
        ] + copy.deepcopy(base)
        g1, m1 = core_scc(shifted)
        assert m0 != m1  # the indices really did move
        assert scc_fingerprint(g0, m0)[0] == scc_fingerprint(g1, m1)[0]

    def test_threshold_sensitive(self):
        base = multi_scc_base()
        g0, m0 = core_scc(base)
        key = core_key(base)
        g1, m1 = core_scc(wobble(base, key))
        assert scc_fingerprint(g0, m0)[0] != scc_fingerprint(g1, m1)[0]

    def test_closedness_reported(self):
        open_core = [
            {"publicKey": k, "name": k,
             "quorumSet": {"threshold": 2,
                           "validators": ["A", "B", "C", "W"]}}
            for k in ("A", "B", "C")
        ] + [{"publicKey": "W", "name": "W", "quorumSet": None}]
        graph, members = core_scc(open_core)
        fp, closed = scc_fingerprint(graph, members)
        assert closed is False
        closed_core = majority_fbas(5)
        g2, m2 = core_scc(closed_core)
        assert scc_fingerprint(g2, m2)[1] is True

    def test_localize_project_round_trip(self):
        members = [3, 7, 11, 20]
        local = localize([11, 3], members)
        assert local == [2, 0]
        assert project(local, members) == [11, 3]
        assert localize([11, 4], members) is None  # escapes the SCC
        assert localize(None, members) is None
        assert project(None, members) is None


class TestDiffSnapshots:
    """old→new SCC mapping: unchanged | dirty | new, merges and splits."""

    def test_rename_is_all_unchanged(self):
        base = multi_scc_base()
        renamed = copy.deepcopy(base)
        for n in renamed:
            n["name"] = (n.get("name") or "") + "~r"
        diff = diff_snapshots(build_graph(parse_fbas(base)),
                              build_graph(parse_fbas(renamed)))
        assert diff.dirty == 0 and diff.new == 0
        assert diff.unchanged == diff.new_n_sccs

    def test_one_wobble_dirties_one(self):
        base = multi_scc_base()
        nxt = wobble(base, core_key(base))
        diff = diff_snapshots(build_graph(parse_fbas(base)),
                              build_graph(parse_fbas(nxt)))
        assert diff.dirty == 1 and diff.new == 0
        assert diff.unchanged == diff.new_n_sccs - 1
        (dirty,) = [d for d in diff.deltas if d.kind == "dirty"]
        assert dirty.size == 6  # the core

    def test_added_node_is_new(self):
        base = multi_scc_base()
        nxt = copy.deepcopy(base) + [{
            "publicKey": "FRESH1", "name": "fresh",
            "quorumSet": {"threshold": 1, "validators": ["FRESH1"]},
        }]
        diff = diff_snapshots(build_graph(parse_fbas(base)),
                              build_graph(parse_fbas(nxt)))
        assert diff.new == 1
        (new,) = [d for d in diff.deltas if d.kind == "new"]
        assert new.old_indices == []

    def test_merge_and_split_counted(self):
        """The invalidation matrix's structural half, against the
        ground-truth annotations of churn_trace_steps (computed by member
        key sets, independently of the differ)."""
        base = multi_scc_base(seed=11)
        trace, metas = churn_trace_steps(
            base, 10, seed=5, max_diff=1,
            kinds=("scc_merge", "scc_split", "threshold"),
        )
        restructured = 0
        for prev, nxt, meta in zip(trace, trace[1:], metas):
            diff = diff_snapshots(build_graph(parse_fbas(prev)),
                                  build_graph(parse_fbas(nxt)))
            assert diff.merges == meta["merges"]
            assert diff.splits == meta["splits"]
            if meta["partition_changed"]:
                restructured += 1
                assert diff.dirty + diff.new >= 1
            if not meta["affected_scc_ids"]:
                assert diff.dirty == 0
        assert restructured >= 2  # the kinds mix really restructured


class TestChurnTraceSteps:
    """Ground-truth step annotations (satellite 1)."""

    def test_deterministic_and_wrapper_identical(self):
        base = multi_scc_base()
        t1, m1 = churn_trace_steps(base, 6, seed=3)
        t2, m2 = churn_trace_steps(base, 6, seed=3)
        assert t1 == t2 and m1 == m2
        assert churn_trace(base, 6, seed=3) == t1

    def test_affected_ids_match_structural_mutations(self):
        base = multi_scc_base()
        _, metas = churn_trace_steps(base, 20, seed=9)
        saw_structural = saw_cosmetic = False
        for meta in metas:
            structural_sccs = {
                m["scc_id"] for m in meta["mutations"]
                if m["structural"] and m["scc_id"] is not None
            }
            assert structural_sccs <= set(meta["affected_scc_ids"])
            if not meta["partition_changed"]:
                assert set(meta["affected_scc_ids"]) == structural_sccs
            if structural_sccs:
                saw_structural = True
            if any(m["kind"] == "rename" for m in meta["mutations"]):
                saw_cosmetic = True
        assert saw_structural and saw_cosmetic

    def test_split_marks_guardward_restructure(self):
        base = multi_scc_base(seed=11)
        _, metas = churn_trace_steps(
            base, 8, seed=2, max_diff=1, kinds=("scc_split",),
        )
        assert any(m["splits"] >= 1 for m in metas)

    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            churn_trace_steps(multi_scc_base(), 1, kinds=("bogus",))


class TestDifferentialChurn:
    """Incremental verdicts + composed certs == from-scratch, every rung."""

    @pytest.mark.parametrize("backend", BACKENDS)
    def test_incremental_equals_scratch(self, rec, backend):
        steps = 10 if backend in ("python", "cpp") else 5
        base = multi_scc_base(seed=7, n_watchers=8)
        trace = churn_trace(base, steps, seed=2)
        engine = DeltaEngine(SccVerdictStore(256))
        inc = [
            engine.check_many([snap], backend=make_backend(backend))[0]
            for snap in trace
        ]
        scratch = check_many(trace, backend=make_backend(backend))
        assert len(inc) == len(scratch) == len(trace)
        composed = 0
        for snap, a, b in zip(trace, inc, scratch):
            assert a.intersects is b.intersects
            if not a.intersects:
                assert a.q1 is not None and a.q2 is not None
                assert {frozenset(a.q1), frozenset(a.q2)} == \
                    {frozenset(b.q1), frozenset(b.q2)}
            # Composed and fresh certs both pass the stdlib checker
            # against the RAW snapshot — the adversarial bar.
            check_certificate(a.cert, snap)
            stamp = a.cert["provenance"]["delta"]
            assert stamp["schema"] == "qi-delta/1"
            composed += stamp["reused_sccs"]
        assert composed >= 1  # churn really exercised reuse
        assert engine.store.reuse_pct() > 0.0

    def test_restructuring_churn_parity(self, rec):
        """Merge/split steps flow through the same differential bar
        (guard flips included) — python rung, the semantics oracle."""
        base = multi_scc_base(seed=11)
        trace = churn_trace(
            base, 8, seed=4,
            kinds=("threshold", "swap", "rename", "scc_merge", "scc_split"),
        )
        engine = DeltaEngine(SccVerdictStore(256))
        inc = [engine.check_many([s], backend="python")[0] for s in trace]
        scratch = check_many(trace, backend="python")
        for snap, a, b in zip(trace, inc, scratch):
            assert a.intersects is b.intersects
            check_certificate(a.cert, snap)

    def test_intra_batch_followers_compose(self, rec):
        """Identical snapshots inside ONE batch: a single leader solve,
        the rest compose from the just-banked fragment."""
        nodes = multi_scc_base()
        counting = CountingOracle()
        engine = DeltaEngine(SccVerdictStore(64), track_diff=False)
        results = engine.check_many([nodes] * 4, backend=counting)
        assert counting.calls == 1
        assert len(results) == 4
        assert len({r.intersects for r in results}) == 1
        assert results[1].cert["provenance"]["delta"]["reused_sccs"] == 1


class TestInvocationPinning:
    """Exactly one SCC reaches a backend on a one-SCC diff."""

    def test_watcher_wobble_resolves_zero(self, rec):
        base = multi_scc_base()
        counting = CountingOracle()
        engine = DeltaEngine(SccVerdictStore(256))
        engine.check_many([base], backend=counting)
        assert counting.calls == 1  # the cold solve
        wobbled = wobble(base, watcher_key(base))
        res = engine.check_many([wobbled], backend=counting)[0]
        assert counting.calls == 1  # nothing new reached a backend
        assert res.cert["provenance"]["delta"]["reused_sccs"] == 1
        counters, _ = rec.snapshot()
        # exactly one SCC's scan re-derived: the wobbled watcher's
        assert counters.get("delta.scan_misses", 0) == \
            res.n_sccs + 1

    def test_core_wobble_resolves_exactly_one(self, rec):
        base = multi_scc_base()
        counting = CountingOracle()
        engine = DeltaEngine(SccVerdictStore(256))
        engine.check_many([base], backend=counting)
        dirtied = wobble(base, core_key(base))
        res = engine.check_many([dirtied], backend=counting)[0]
        assert counting.calls == 2  # cold solve + exactly the dirty core
        assert res.cert["provenance"]["delta"]["resolved_sccs"] == 1
        assert res.intersects is solve(
            dirtied, backend="python").intersects

    def test_merge_invalidates_core_fragment(self, rec):
        """SCC merge/split invalidation matrix, solver-counter half: a
        core merged with a watcher is a NEW structural problem — the old
        fragment must not answer it."""
        base = multi_scc_base()
        counting = CountingOracle()
        engine = DeltaEngine(SccVerdictStore(256))
        engine.check_many([base], backend=counting)
        assert counting.calls == 1
        graph, members = core_scc(base)
        ckey = graph.node_ids[members[0]]
        wkey = watcher_key(base)
        merged = copy.deepcopy(base)
        for n in merged:
            if n["publicKey"] == ckey:
                n["quorumSet"]["validators"].append(wkey)
            elif n["publicKey"] == wkey:
                n["quorumSet"]["validators"].append(ckey)
        g2, m2 = core_scc(merged)
        assert len(m2) == len(members) + 1  # the merge really happened
        res = engine.check_many([merged], backend=counting)[0]
        assert counting.calls == 2  # re-solved, not served stale
        assert res.intersects is solve(merged, backend="python").intersects
        # ... and the merged fragment now serves its own repeats.
        engine.check_many([copy.deepcopy(merged)], backend=counting)
        assert counting.calls == 2

    def test_split_flips_to_guard_not_stale(self, rec):
        """Splitting a self-sufficient slice off the core yields >= 2
        quorum-bearing SCCs: the guard decides, no stale fragment may."""
        base = multi_scc_base()
        graph, members = core_scc(base)
        ckey = graph.node_ids[members[0]]
        split = copy.deepcopy(base)
        for n in split:
            if n["publicKey"] == ckey:
                n["quorumSet"] = {"threshold": 1, "validators": [ckey]}
        engine = DeltaEngine(SccVerdictStore(256))
        engine.check_many([base], backend="python")
        res = engine.check_many([split], backend="python")[0]
        oracle = solve(split, backend="python")
        assert res.intersects is oracle.intersects is False
        assert res.stats.get("reason") == "scc_guard"
        check_certificate(res.cert, split)


class TestSoundnessGate:
    """A non-closed SCC's verdict is only reusable under scope_to_scc."""

    OPEN = [
        {"publicKey": k, "name": k,
         "quorumSet": {"threshold": 2, "validators": ["A", "B", "C", "W"]}}
        for k in ("A", "B", "C")
    ] + [{"publicKey": "W", "name": "W", "quorumSet": None}]

    def test_open_scc_never_cached_whole_graph(self, rec):
        counting = CountingOracle()
        engine = DeltaEngine(SccVerdictStore(64), track_diff=False)
        for _ in range(3):
            engine.check_many([copy.deepcopy(self.OPEN)], backend=counting)
        assert counting.calls == 3  # every repeat re-solved
        counters, _ = rec.snapshot()
        assert counters.get("delta.uncacheable", 0) == 3

    def test_open_scc_cached_when_scoped(self, rec):
        counting = CountingOracle()
        engine = DeltaEngine(
            SccVerdictStore(64), scope_to_scc=True, track_diff=False,
        )
        for _ in range(3):
            engine.check_many([copy.deepcopy(self.OPEN)], backend=counting)
        assert counting.calls == 1


class TestFaultDegrade:
    """delta.diff failure degrades to the full chain, verdicts unchanged."""

    def test_diff_fault_full_resolve_parity(self, rec):
        faults.install_plan(faults.parse_faults("delta.diff=error@1+"))
        base = multi_scc_base()
        trace = churn_trace(base, 3, seed=1)
        engine = DeltaEngine(SccVerdictStore(64))
        inc = [engine.check_many([s], backend="python")[0] for s in trace]
        faults.clear_plan()
        scratch = check_many(trace, backend="python")
        for a, b in zip(inc, scratch):
            assert a.intersects is b.intersects
        counters, _ = rec.snapshot()
        assert counters.get("delta.diff_faults", 0) == len(trace)
        assert len(engine.store) == 0  # degraded runs never touch the store

    def test_fault_then_recovery_reuses(self, rec):
        faults.install_plan(faults.parse_faults("delta.diff=error@1"))
        base = multi_scc_base()
        engine = DeltaEngine(SccVerdictStore(64))
        counting = CountingOracle()
        engine.check_many([base], backend=counting)  # degraded (fault @1)
        engine.check_many([base], backend=counting)  # delta path, cold
        engine.check_many([base], backend=counting)  # delta path, reuse
        assert counting.calls == 2


class TestStore:
    """LRU bound, occupancy gauge, lease cycle."""

    def test_lru_bound_and_evictions(self, rec):
        store = SccVerdictStore(2)
        for i in range(4):
            store.put_scan(f"fp{i}", SccScan(quorum_local=(0,)))
        assert len(store) == 2
        assert store.get_scan("fp0") is None  # the oldest fell out
        assert store.get_scan("fp3") is not None
        counters, gauges = rec.snapshot()
        assert counters.get("delta.store_evictions", 0) == 2
        assert gauges.get("delta.store_size") == 2

    def test_env_knob_bounds_store(self, rec, monkeypatch):
        monkeypatch.setenv("QI_DELTA_CACHE_MAX", "3")
        assert SccVerdictStore().max_entries == 3

    def test_lease_cycle(self, rec):
        store = SccVerdictStore(8)
        outcome, cached = store.lease_verdict("fpX", False)
        assert outcome == "leader" and cached is None
        from quorum_intersection_tpu.delta import SccVerdict

        store.publish_verdict("fpX", False, SccVerdict(
            intersects=True, q1_local=None, q2_local=None,
        ))
        outcome, cached = store.lease_verdict("fpX", False)
        assert outcome == "hit" and cached.intersects is True
        # scope_to_scc is part of the key: same fp, different scoping.
        outcome, _ = store.lease_verdict("fpX", True)
        assert outcome == "leader"
        store.publish_verdict("fpX", True, None)  # failed lease: no entry
        outcome, _ = store.lease_verdict("fpX", True)
        assert outcome == "leader"


class TestServeIntegration:
    """The serve drain consults qi-delta; the gauges reach /healthz."""

    def test_serve_churn_reuses_and_matches(self, rec):
        from quorum_intersection_tpu.serve import ServeEngine

        base = multi_scc_base()
        trace = churn_trace(base, 6, seed=3)
        oracle = [solve(s, backend="python").intersects for s in trace]
        engine = ServeEngine(backend="python")
        assert engine._delta is not None  # on by default
        try:
            engine.start()
            for snap, expected in zip(trace, oracle):
                resp = engine.submit(snap).result(timeout=60.0)
                assert resp.intersects is expected
        finally:
            engine.stop(drain=True, timeout=30.0)
        assert engine._delta.store.reuse_pct() > 0.0
        counters, gauges = rec.snapshot()
        assert counters.get("delta.compositions", 0) >= 1
        assert gauges.get("delta.scc_reuse_pct", 0.0) > 0.0

    def test_serve_delta_off_switch(self, rec, monkeypatch):
        from quorum_intersection_tpu.serve import ServeEngine

        assert ServeEngine(backend="python", delta=False)._delta is None
        monkeypatch.setenv("QI_DELTA_CACHE_MAX", "0")
        assert ServeEngine(backend="python")._delta is None

    def test_healthz_exposes_delta_gauges(self, rec):
        from quorum_intersection_tpu.utils.metrics_server import (
            healthz_payload,
        )

        engine = DeltaEngine(SccVerdictStore(64), track_diff=False)
        nodes = multi_scc_base()
        engine.check_many([nodes], backend="python")
        engine.check_many([copy.deepcopy(nodes)], backend="python")
        payload = healthz_payload()
        assert payload["delta_scc_reuse_pct"] == 50.0
        assert payload["delta_store_size"] >= 1
