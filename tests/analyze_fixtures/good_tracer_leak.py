"""qi-lint fixture twin: the same computation with trace-safe control flow
(jnp.where on the traced value; Python ``if`` only on static closure
config, which the rule must NOT flag)."""

import jax
import jax.numpy as jnp

USE_ABS = True


@jax.jit
def safe_step(avail):
    votes = jnp.sum(avail, axis=-1)
    if USE_ABS:  # static module constant: fine at trace time
        return jnp.where(votes > 0, votes, -votes)
    return votes
