"""Fixture twin: blocking work outside the lock; the sanctioned
condition-wait on the innermost held lock stays unflagged."""

import subprocess
import threading


class Builder:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._done = threading.Condition(self._lock)
        self.artifacts = []

    def build(self) -> None:
        subprocess.run(["true"], check=False)
        with self._lock:
            self.artifacts.append("built")

    def wait_built(self) -> None:
        with self._done:
            self._done.wait_for(lambda: bool(self.artifacts))
