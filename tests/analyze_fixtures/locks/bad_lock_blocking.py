"""Fixture: a subprocess run while holding the class lock."""

import subprocess
import threading


class Builder:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.artifacts = []

    def build(self) -> None:
        with self._lock:
            subprocess.run(["true"], check=False)  # BAD: blocks every waiter
            self.artifacts.append("built")
