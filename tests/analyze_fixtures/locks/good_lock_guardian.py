"""Fixture twin: every mutation of the attribute holds its guardian."""

import threading


class Collector:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.items = []
        self._thread = threading.Thread(target=self._worker)

    def add_item(self, x: object) -> None:
        with self._lock:
            self.items.append(x)

    def _worker(self) -> None:
        with self._lock:
            self.items.append("tick")
