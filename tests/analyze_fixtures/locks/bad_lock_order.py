"""Fixture: inconsistent lock order across interprocedural call edges."""

import threading


class Orderer:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._other = threading.Lock()

    def ab(self) -> None:
        with self._lock:
            self._grab_other()  # BAD: _lock then _other ...

    def _grab_other(self) -> None:
        with self._other:
            pass

    def ba(self) -> None:
        with self._other:
            self._grab_lock()  # BAD: ... while this path takes _other then _lock

    def _grab_lock(self) -> None:
        with self._lock:
            pass
