"""Fixture twin: the same two locks, always acquired in one global order."""

import threading


class Orderer:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._other = threading.Lock()

    def ab(self) -> None:
        with self._lock:
            self._grab_other()

    def _grab_other(self) -> None:
        with self._other:
            pass

    def ba(self) -> None:
        with self._lock:
            with self._other:
                pass
