"""Fixture: a guarded attribute mutated lock-free on the worker thread."""

import threading


class Collector:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.items = []
        self._thread = threading.Thread(target=self._worker)

    def add_item(self, x: object) -> None:
        with self._lock:
            self.items.append(x)

    def _worker(self) -> None:
        self.items.append("tick")  # BAD: lock-free on the spawned thread
