"""Fixture: one emission of every unregistered/undeclared kind."""

from quorum_intersection_tpu.utils.env import qi_env
from quorum_intersection_tpu.utils.faults import fault_point
from quorum_intersection_tpu.utils.telemetry import get_run_record


def emit() -> None:
    rec = get_run_record()
    rec.add("fixture.unregistered")  # BAD: counter missing from the registry
    fault_point("fixture.undeclared")  # BAD: not in the fault catalog
    qi_env("QI_UNDECLARED")  # BAD: not in the env registry
