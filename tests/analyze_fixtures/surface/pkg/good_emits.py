"""Fixture: emissions that exactly match the synthetic registries."""

from quorum_intersection_tpu.utils.env import qi_env
from quorum_intersection_tpu.utils.faults import fault_point
from quorum_intersection_tpu.utils.telemetry import get_run_record


def emit(name: str) -> None:
    rec = get_run_record()
    rec.add("fixture.registered")
    rec.gauge("fixture.gauge", 1.0)
    rec.event("fixture.event")
    with rec.span("fixture.span"):
        with rec.span(f"fixture.dyn.{name}"):
            pass
    fault_point("fixture.point")
    qi_env("QI_FIXTURE")
