"""qi-lint fixture: a RunRecord span opened by hand — an exception between
``__enter__`` and ``__exit__`` leaks the enter and the telemetry stream
ends with a dangling span."""

from quorum_intersection_tpu.utils.telemetry import get_run_record


def solve_with_leaky_span(work):
    sp = get_run_record().span("phase.search")  # BAD: not a `with` item
    sp.__enter__()
    try:
        return work()
    finally:
        sp.__exit__(None, None, None)
