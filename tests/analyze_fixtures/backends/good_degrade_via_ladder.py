"""Fixture twin: every broad catch in ``backends/`` is sanctioned — the
ladder's own handler, a typed re-raise, a handler that reports through the
ladder API, or a reviewed allow() for pure cleanup."""


class RungFailed(RuntimeError):
    pass


class DegradationLadder:
    def attempt(self, rung, fn):
        try:
            return fn()
        except Exception as exc:  # the ladder's one sanctioned broad catch
            raise RungFailed(rung) from exc


def route(backend, ladder):
    try:
        return backend.check_scc()
    except Exception as exc:  # reports the transition through the ladder
        ladder.record_degrade("tpu-sweep", "host-oracle", exc)
        return None


def surface(backend):
    try:
        return backend.check_scc()
    except Exception as exc:  # re-raised typed: loud, never silent
        raise RungFailed("tpu-sweep") from exc


def cleanup(checkpoint):
    try:
        checkpoint.clear()
    # qi-lint: allow(degrade-via-ladder) — cleanup is best-effort
    except Exception:
        pass
