"""Fixture: ad-hoc broad catch-and-fall-through in ``backends/`` — the
pre-ISSUE-4 pattern the ``degrade-via-ladder`` rule forbids (an engine
failure silently swallowed with no retry budget, no quarantine, and no
``degrade`` telemetry event)."""


def route(backend):
    try:
        return backend.check_scc()
    except Exception:  # BAD: swallowed degradation outside the ladder
        return None
