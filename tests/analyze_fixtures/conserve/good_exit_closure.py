"""GOOD twin: every exit path books exactly one closure leg."""


def resolve(rec, entry, verdict):
    if entry.cancelled:
        rec.add("serve.errors", 1)
        return None
    rec.add("serve.verdicts", 1)
    return verdict
