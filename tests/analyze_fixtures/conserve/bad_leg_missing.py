"""BAD twin: the except arm books only the ledger leg of the cancel pair."""


def drain(rec, jobs):
    done = 0
    for job in jobs:
        try:
            job.run()
            rec.add("sweep.windows_cancelled", 0)
            rec.add("cert.windows_cancelled", 0)
            done += 1
        except RuntimeError:
            rec.add("cert.windows_cancelled", 1)
            return done  # BAD: exits with only the ledger twin booked
    return done
