"""GOOD twin: every path that touches the pair books both legs."""


def drain(rec, jobs):
    done = 0
    for job in jobs:
        try:
            job.run()
            rec.add("sweep.windows_cancelled", 0)
            rec.add("cert.windows_cancelled", 0)
            done += 1
        except RuntimeError:
            rec.add("sweep.windows_cancelled", 1)
            rec.add("cert.windows_cancelled", 1)
            return done
    return done
