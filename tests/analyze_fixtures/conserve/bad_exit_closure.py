"""BAD twin: the early return delivers no closure leg — a silent drop."""


def resolve(rec, entry, verdict):
    if entry.cancelled:
        return None  # BAD: neither verdicts nor errors booked on this exit
    rec.add("serve.verdicts", 1)
    return verdict
