"""qi-lint fixture: a worker thread spawned with no CancelToken anywhere in
reach — once the race is decided, nobody can stop this work."""

import threading


def spawn_unstoppable_worker(job):
    worker = threading.Thread(target=job, name="qi-fixture-worker")  # BAD
    worker.start()
    return worker
