"""qi-lint fixture: a bare ``QI_*`` env read — the knob exists in code but
not in the registry, so the documented catalog silently rots."""

import os


def undocumented_knob():
    return os.environ.get("QI_SECRET_TUNING", "0")  # BAD: not via qi_env
