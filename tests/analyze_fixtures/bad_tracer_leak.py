"""qi-lint fixture: the jax-tracer-leak failure mode, distilled.

Never imported — the lint pass parses it.  The Python ``if`` on a traced
reduction is exactly the bug class that silently bakes one branch into the
compiled program (or crashes at trace time) in encode/circuit.py-style
kernels."""

import jax
import jax.numpy as jnp


@jax.jit
def leaky_step(avail):
    votes = jnp.sum(avail, axis=-1)
    if votes > 0:  # BAD: trace-time branch on a traced value
        return votes
    return -votes
