"""qi-lint fixture: a cheap stdlib module imported at function level — the
shape backends/auto.py:349 had before ISSUE 3's first satellite moved it
to module scope."""


def racy_section():
    import threading  # BAD: threading costs nothing at import time

    return threading.Event()
