"""Fixture: a telemetry name built at runtime — invisible to qi-surface."""

from quorum_intersection_tpu.utils.telemetry import get_run_record


def emit(kind: str) -> None:
    rec = get_run_record()
    rec.add("fixture." + kind)  # BAD: concatenation is not statically resolvable
