"""qi-lint fixture: a telemetry-style counter mutated outside its lock —
the racing auto router's two threads both increment, and unlocked
read-modify-write drops counts."""

import threading


class MiniRecord:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {}

    def add(self, name, n=1):
        self.counters[name] = self.counters.get(name, 0) + n  # BAD: no lock
