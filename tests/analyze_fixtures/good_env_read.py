"""qi-lint fixture twin: the read goes through the registry (and non-QI_
env vars — jax's own knobs, CI plumbing — stay out of the rule's scope)."""

import os

from quorum_intersection_tpu.utils.env import qi_env


def documented_knob():
    return qi_env("QI_LOG_LEVEL")


def foreign_knob():
    return os.environ.get("JAX_PLATFORMS")  # not QI_*: out of scope
