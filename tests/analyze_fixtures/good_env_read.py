"""qi-lint fixture twin: the read goes through the registry (and non-QI_
env vars — jax's own knobs, CI plumbing — stay out of the rule's scope)."""

import os

from quorum_intersection_tpu.utils.env import qi_env


def documented_knob():
    return qi_env("QI_LOG_LEVEL")


def sweep_reduction_knobs():
    # ISSUE 10: the two pruned-sweep knobs are registry-declared — a read
    # through qi_env is the documented (and lint-clean) access path.
    return qi_env("QI_SWEEP_ORDER"), qi_env("QI_SWEEP_PRUNE")


def foreign_knob():
    return os.environ.get("JAX_PLATFORMS")  # not QI_*: out of scope
