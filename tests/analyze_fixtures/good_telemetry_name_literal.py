"""Fixture twin: every statically-resolvable name shape the rule allows."""

from quorum_intersection_tpu.utils.faults import fault_point
from quorum_intersection_tpu.utils.telemetry import get_run_record

FIXTURE_COUNTER = "fixture.counter"


def emit(flag: bool) -> None:
    rec = get_run_record()
    rec.add(FIXTURE_COUNTER)  # module-level constant
    rec.add("fixture.hits" if flag else "fixture.misses")  # both branches literal
    rec.event(f"fixture.{'on' if flag else 'off'}")  # dotted-prefix f-string
    rec.gauge("fixture.gauge", 1.0)  # plain literal
    fault_point("checkpoint.write")  # literal catalog key
