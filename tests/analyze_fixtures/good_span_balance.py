"""qi-lint fixture twin: the span enters as a ``with`` item, so every exit
path — including exceptions — closes it."""

from quorum_intersection_tpu.utils.telemetry import get_run_record


def solve_with_balanced_span(work):
    with get_run_record().span("phase.search") as sp:
        result = work()
        sp.set(ok=True)
        return result
