"""qi-lint fixture twin: the spawner accepts and forwards a CancelToken, so
the race driver can reach the work it started."""

import threading

from quorum_intersection_tpu.backends.base import CancelToken


def spawn_cancellable_worker(job, cancel: CancelToken):
    def run():
        if not cancel.cancelled:
            job(cancel)

    worker = threading.Thread(target=run, name="qi-fixture-worker")
    worker.start()
    return worker
