"""qi-lint fixture twin: stdlib at module scope; jax and package-internal
imports may stay lazy (the repo's import discipline the rule must not
break), and the suppression syntax is honored when a lazy stdlib import is
genuinely justified."""

import threading


def racy_section():
    return threading.Event()


def device_section():
    import jax  # lazy heavyweight import: allowed by design

    return jax.default_backend()


def suppressed_section():
    # qi-lint: allow(import-at-top) — demonstrates the suppression syntax
    import subprocess

    return subprocess.DEVNULL
