"""qi-lint fixture twin: the same counter, mutated under its lock."""

import threading


class MiniRecord:
    def __init__(self):
        self._lock = threading.Lock()
        self.counters = {}

    def add(self, name, n=1):
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def snapshot(self):
        with self._lock:
            return dict(self.counters)  # reads copy out under the lock too
