"""GOOD twin: accumulation stays on device; no readback in the loop."""

import jax
import jax.numpy as jnp


def _kernel(x):
    return jnp.sum(x * x)


def drive(rec, xs):
    entry = jax.jit(_kernel)
    with rec.span("sweep.drive"):
        total = entry(xs)
        return total
