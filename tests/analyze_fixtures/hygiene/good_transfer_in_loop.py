"""GOOD twin: the table uploads once, above the hot loop."""

import jax
import jax.numpy as jnp


def _kernel(x):
    return jnp.sum(x * x)


def drive(rec, table, xs):
    entry = jax.jit(_kernel)
    w = jnp.asarray(table)
    with rec.span("sweep.drive"):
        outs = []
        for x in xs:
            outs.append(entry(w))
        return outs
