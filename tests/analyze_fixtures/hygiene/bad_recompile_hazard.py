"""BAD twin: the jit entry sees a new operand shape per iteration."""

import jax
import jax.numpy as jnp


def _kernel(x):
    return jnp.sum(x * x)


def drive(rec, sizes):
    entry = jax.jit(_kernel)
    with rec.span("sweep.drive"):
        outs = []
        for n in sizes:
            outs.append(entry(jnp.zeros(n)))  # BAD: one compile per size
        return outs
