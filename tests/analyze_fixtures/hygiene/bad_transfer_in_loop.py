"""BAD twin: the same host table re-uploads on every hot iteration."""

import jax
import jax.numpy as jnp


def _kernel(x):
    return jnp.sum(x * x)


def drive(rec, table, xs):
    entry = jax.jit(_kernel)
    with rec.span("sweep.drive"):
        outs = []
        for x in xs:
            w = jnp.asarray(table)  # BAD: loop-invariant upload per pass
            outs.append(entry(w))
        return outs
