"""BAD twin: a per-window host readback inside the hot drive loop."""

import jax
import jax.numpy as jnp


def _kernel(x):
    return jnp.sum(x * x)


def drive(rec, xs):
    entry = jax.jit(_kernel)
    with rec.span("sweep.drive"):
        total = 0.0
        for x in xs:
            y = entry(x)
            total += float(y)  # BAD: hidden device sync every window
        return total
