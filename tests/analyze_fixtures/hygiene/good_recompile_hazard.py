"""GOOD twin: operand sizes route through the canonical pad ladder."""

import jax
import jax.numpy as jnp

from quorum_intersection_tpu.encode.circuit import ladder_up


def _kernel(x):
    return jnp.sum(x * x)


def drive(rec, sizes):
    entry = jax.jit(_kernel)
    with rec.span("sweep.drive"):
        outs = []
        for n in sizes:
            outs.append(entry(jnp.zeros(ladder_up(n))))
        return outs
