"""Fixture: a consumer reading a field no producer of the channel writes."""


def produce(x: object) -> dict:
    return {"a": x, "kind": "row"}


def consume(obj: dict) -> object:
    if obj.get("kind") != "row":
        return None
    return obj.get("missing")  # BAD: nobody produces "missing"
