"""Fixture twin: every consumed field is produced."""


def produce(x: object) -> dict:
    out = {"a": x, "kind": "row"}
    out["b"] = repr(x)
    return out


def consume(obj: dict) -> object:
    if "kind" in obj:
        return (obj.get("a"), obj["b"])
    return None
