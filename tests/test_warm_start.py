"""Warm-start compile path (ISSUE 1 acceptance): with a hot persistent
compilation cache, the second sweep run of the same canonical shape must
record an XLA-compile phase <= 10% of the cold run's.

Runs in SUBPROCESSES against a tmp-dir cache: the cache-enable hook is
idempotent per process (utils/compile_cache._installed) and the suite's own
sweeps would otherwise have already decided it, and a fresh process is
exactly the scenario the persistent cache exists for.
"""

import json
import os
import subprocess
import sys

# Deeply nested qsets: node_sat unrolls `depth` child-propagation matmuls,
# so the single compiled program is HEAVY (~2 s cold on CPU) while the
# warm-path fixed costs (cache-key hashing + executable deserialization,
# ~0.1 s) stay small — the ratio the 10% bar measures is then dominated by
# the cache hit, not by harness noise.  batch=512 on the 2^12 enumeration
# keeps the run single-program (no ramp jump), so exactly one compile is
# measured per run.
_CHILD = r"""
import json, sys
from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
from quorum_intersection_tpu.pipeline import solve

def deep_qset(validators, k, d):
    q = {"threshold": k, "validators": list(validators)}
    for _ in range(d):
        q = {"threshold": 1, "innerQuorumSets": [q]}
    return q

names = [f"N{i}" for i in range(13)]
data = [{"publicKey": nm, "quorumSet": deep_qset(names, 7, 64)} for nm in names]
res = solve(data, backend=TpuSweepBackend(batch=512))
print(json.dumps({
    "intersects": res.intersects,
    "xla_compile_seconds": res.stats["xla_compile_seconds"],
    "padded_shape": res.stats.get("padded_shape"),
}))
"""


def _run(cache_dir):
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        QI_COMPILE_CACHE_CPU="1",
        JAX_COMPILATION_CACHE_DIR=str(cache_dir),
    )
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD],
        capture_output=True, text=True, timeout=600, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_second_run_compile_phase_within_10pct_of_cold(tmp_path):
    cache = tmp_path / "jax_cache"
    cache.mkdir()
    cold = _run(cache)
    assert cold["intersects"] is True
    assert cold["xla_compile_seconds"] > 0, "cold run recorded no compile"
    # The canonical pad ladder is what makes the shape repeatable.
    assert cold["padded_shape"] == [16, 96]
    assert any(cache.iterdir()), "persistent cache stayed empty"

    warm = _run(cache)
    assert warm["intersects"] is True
    assert warm["padded_shape"] == cold["padded_shape"]
    assert warm["xla_compile_seconds"] <= 0.10 * cold["xla_compile_seconds"], (
        f"warm compile {warm['xla_compile_seconds']}s vs "
        f"cold {cold['xla_compile_seconds']}s"
    )
