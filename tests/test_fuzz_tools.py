"""The randomized fuzz harnesses stay runnable: tiny no-ledger windows of
both tools execute end-to-end with zero failures.  (The tools earn their
keep — each caught a real bug on first contact, see docs/ROUND5_NOTES.md —
so a broken harness is lost coverage the curated corpus won't replace.)"""

import sys

import pytest


def _run_main(module, argv):
    old = sys.argv
    sys.argv = argv
    try:
        return module.main()
    finally:
        sys.argv = old


def test_fuzz_python_smoke_window():
    from tools import fuzz_python

    rc = _run_main(fuzz_python, [
        "fuzz_python.py", "--cases", "120", "--seed", "42", "--no-ledger",
    ])
    assert rc == 0  # zero failures


def test_fuzz_native_smoke_window():
    from quorum_intersection_tpu.backends.cpp import build_native_cli

    try:
        build_native_cli(sanitize=True)
    except Exception as exc:  # pragma: no cover - g++/libasan missing
        pytest.skip(f"sanitized build unavailable: {exc}")
    from tools import fuzz_native

    rc = _run_main(fuzz_native, [
        "fuzz_native.py", "--cases", "40", "--seed", "42", "--no-ledger",
    ])
    assert rc == 0
