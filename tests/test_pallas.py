"""Differential tests: the fused Pallas sweep engine must agree with the XLA
sweep path program-for-program (same min-hit-index contract) and end-to-end.

On CPU the kernel runs in pallas interpret mode (pallas_sweep auto-detects
the backend), so these tests validate the kernel logic without TPU hardware —
the TPU-side compile is exercised by the benchmarks on the real chip.
"""

import numpy as np
import pytest

from quorum_intersection_tpu.backends.tpu import pallas_sweep
from quorum_intersection_tpu.backends.tpu.kernels import sweep_program_factory
from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
from quorum_intersection_tpu.encode.circuit import encode_circuit
from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.semantics import max_quorum
from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas
from quorum_intersection_tpu.pipeline import solve


def _sweep_inputs(data):
    graph = build_graph(parse_fbas(data))
    circuit = encode_circuit(graph)
    count, comp = tarjan_scc(graph.n, graph.succ)
    sccs = group_sccs(graph.n, comp, count)
    scc = next(
        m
        for m in sccs
        if max_quorum(graph, m, [v in set(m) for v in range(graph.n)])
    )
    n = circuit.n
    scc_mask = np.zeros(n, dtype=np.float32)
    scc_mask[scc] = 1.0
    frozen = 1.0 - scc_mask
    bit_nodes = np.asarray(scc[1:], dtype=np.int32)
    return circuit, bit_nodes, scc_mask, frozen


@pytest.mark.parametrize(
    "data",
    [
        majority_fbas(9),
        majority_fbas(10, broken=True),
        hierarchical_fbas(4, 3),  # nested inner sets (depth ≥ 1)
        hierarchical_fbas(3, 3, broken=True),
    ],
    ids=["maj-safe", "maj-broken", "hier-safe", "hier-broken"],
)
def test_program_parity_with_xla(data):
    circuit, bit_nodes, scc_mask, frozen = _sweep_inputs(data)
    total = 1 << len(bit_nodes)
    batch, _ = pallas_sweep.plan_batch(min(total, 128))
    xla = sweep_program_factory(circuit, bit_nodes, scc_mask, frozen, batch)(1)
    pal = pallas_sweep.pallas_sweep_program_factory(
        circuit, bit_nodes, scc_mask, frozen, batch
    )(1)
    for start in range(0, total, batch):
        assert int(xla(start)) == int(pal(start)), f"divergence at start={start}"


def test_program_parity_multi_step():
    circuit, bit_nodes, scc_mask, frozen = _sweep_inputs(majority_fbas(11, broken=True))
    batch, _ = pallas_sweep.plan_batch(64)
    xla = sweep_program_factory(circuit, bit_nodes, scc_mask, frozen, batch)(4)
    pal = pallas_sweep.pallas_sweep_program_factory(
        circuit, bit_nodes, scc_mask, frozen, batch
    )(4)
    assert int(xla(0)) == int(pal(0))


@pytest.mark.parametrize("broken", [False, True])
def test_backend_end_to_end(broken):
    data = majority_fbas(9, broken=broken)
    res = solve(data, backend=TpuSweepBackend(batch=64, engine="pallas"))
    assert res.intersects is (not broken)
    if broken:
        assert res.q1 and res.q2
        assert not set(res.q1) & set(res.q2)


def test_unsupported_circuit_rejected():
    # >127 repeats of one validator would overflow int8 votes
    data = [
        {
            "publicKey": "A",
            "quorumSet": {"threshold": 1, "validators": ["A"] * 130},
        }
    ]
    graph = build_graph(parse_fbas(data))
    circuit = encode_circuit(graph)
    assert not pallas_sweep.pallas_supported(circuit)
    with pytest.raises(ValueError):
        pallas_sweep.pallas_sweep_program_factory(
            circuit, np.asarray([], dtype=np.int32), np.ones(1, np.float32), None, 32
        )


def test_plan_batch_contract():
    for req in (1, 16, 32, 100, 1024, 5000, 32768):
        batch, block = pallas_sweep.plan_batch(req)
        assert batch % block == 0
        assert block % 32 == 0
        assert batch >= req


def test_engine_falls_back_for_unsupported_circuit():
    # backend-level contract: engine="pallas" still solves int8-overflow
    # circuits by degrading to the XLA path
    data = [
        {"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["A"] * 130 + ["B"]}},
        {"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["A"]}},
    ]
    res = solve(data, backend=TpuSweepBackend(engine="pallas"))
    assert res.intersects is True
