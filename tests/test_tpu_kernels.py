"""Differential tests: JAX circuit kernels vs the NumPy specification and the
host set semantics (SURVEY.md §4.3 items 2/4)."""

import numpy as np
import pytest

from quorum_intersection_tpu.backends.tpu.kernels import (
    CircuitArrays,
    make_batch_fixpoint,
    subset_masks,
)
from quorum_intersection_tpu.encode.circuit import encode_circuit, max_quorum_np, node_sat_np
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.semantics import max_quorum, slice_satisfied
from quorum_intersection_tpu.fbas.synth import hierarchical_fbas, majority_fbas, random_fbas


def _circuit(data):
    g = build_graph(parse_fbas(data))
    return g, encode_circuit(g)


def _random_avail(rng, batch, n):
    return (rng.random((batch, n)) < 0.6).astype(np.float32)


@pytest.mark.parametrize(
    "data",
    [
        majority_fbas(6),
        hierarchical_fbas(3, 3),
        random_fbas(17, seed=3, nested_prob=0.5, null_prob=0.15, dangling_prob=0.2),
    ],
    ids=["majority", "hierarchical", "random-nested"],
)
def test_node_sat_matches_host_semantics(data):
    g, circuit = _circuit(data)
    rng = np.random.default_rng(0)
    avail = _random_avail(rng, 32, g.n)
    import jax.numpy as jnp

    from quorum_intersection_tpu.backends.tpu.kernels import node_sat

    arrays = CircuitArrays(circuit)
    got = np.asarray(node_sat(arrays, jnp.asarray(avail))) > 0.5
    want_np = node_sat_np(circuit, avail.astype(bool))
    np.testing.assert_array_equal(got, want_np)
    # and the NumPy spec itself against the per-node host semantics
    for b in range(avail.shape[0]):
        av = avail[b].astype(bool).tolist()
        for v in range(g.n):
            assert want_np[b, v] == (av[v] and slice_satisfied(v, g.qsets[v], av))


@pytest.mark.parametrize(
    "data",
    [
        majority_fbas(8),
        hierarchical_fbas(3, 3),
        random_fbas(20, seed=7, nested_prob=0.4, null_prob=0.1),
    ],
    ids=["majority", "hierarchical", "random-nested"],
)
def test_fixpoint_matches_host_semantics(data):
    g, circuit = _circuit(data)
    rng = np.random.default_rng(1)
    avail = _random_avail(rng, 24, g.n)
    run = make_batch_fixpoint(circuit)
    got = run(avail)
    want = max_quorum_np(circuit, avail.astype(bool))
    np.testing.assert_array_equal(got, want)
    for b in range(avail.shape[0]):
        av = avail[b].astype(bool).tolist()
        candidates = [v for v in range(g.n) if av[v]]
        host = sorted(max_quorum(g, candidates, list(av)))
        assert sorted(np.nonzero(got[b])[0].tolist()) == host


def test_fixpoint_frozen_mask_q6_semantics():
    # Node T (outside the "SCC") helps node A satisfy its slice but must never
    # be filtered: frozen reproduces the reference's whole-graph availability.
    data = [
        {"publicKey": "A", "quorumSet": {"threshold": 2, "validators": ["A", "T"]}},
        {"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["B"]}},
        {"publicKey": "T", "quorumSet": None},  # null qset: own slice unsatisfiable
    ]
    g, circuit = _circuit(data)
    run = make_batch_fixpoint(circuit)
    # candidates {A}: without frozen help, A's slice (needs T) fails.
    avail = np.zeros((1, 3), dtype=np.float32)
    avail[0, 0] = 1.0
    assert run(avail).sum() == 0
    # with T frozen-available, A survives even though T's own slice never can.
    frozen = np.array([0.0, 0.0, 1.0], dtype=np.float32)
    got = run(avail, np.broadcast_to(frozen, (1, 3)).copy())
    assert np.nonzero(got[0])[0].tolist() == [0]


def test_fixpoint_empty_and_full():
    g, circuit = _circuit(majority_fbas(5))
    run = make_batch_fixpoint(circuit)
    batch = np.stack(
        [np.zeros(5, np.float32), np.ones(5, np.float32)]
    )
    got = run(batch)
    assert got[0].sum() == 0
    assert got[1].sum() == 5


def test_subset_masks_decoding():
    import jax.numpy as jnp

    bit_nodes = jnp.asarray([4, 1, 6], dtype=jnp.int32)
    masks = np.asarray(subset_masks(jnp.int32(0), 8, bit_nodes, 8))
    # index 5 = 0b101 → bits 0 and 2 → nodes 4 and 6
    assert np.nonzero(masks[5])[0].tolist() == [4, 6]
    assert np.nonzero(masks[0])[0].tolist() == []
    assert np.nonzero(masks[7])[0].tolist() == [1, 4, 6]


def test_subset_masks_offset():
    import jax.numpy as jnp

    bit_nodes = jnp.asarray([0, 1], dtype=jnp.int32)
    masks = np.asarray(subset_masks(jnp.int32(2), 2, bit_nodes, 4))
    assert np.nonzero(masks[0])[0].tolist() == [1]  # index 2 = 0b10
    assert np.nonzero(masks[1])[0].tolist() == [0, 1]  # index 3


@pytest.mark.parametrize(
    "data",
    [
        majority_fbas(8),
        hierarchical_fbas(3, 3),
        random_fbas(20, seed=7, nested_prob=0.4, null_prob=0.1),
    ],
    ids=["majority", "hierarchical", "random-nested"],
)
def test_fixpoint_iters_matches_fixpoint(data):
    # The instrumented variant (bench roofline) must return the SAME
    # fixpoint as the production kernel, plus a positive trip count that
    # can only grow with a batch that converges slower.
    import jax.numpy as jnp

    from quorum_intersection_tpu.backends.tpu.kernels import fixpoint, fixpoint_iters

    g, circuit = _circuit(data)
    arrays = CircuitArrays(circuit)
    rng = np.random.default_rng(5)
    avail = _random_avail(rng, 16, g.n)
    want = np.asarray(fixpoint(arrays, jnp.asarray(avail)))
    got, trips = fixpoint_iters(arrays, jnp.asarray(avail))
    np.testing.assert_array_equal(np.asarray(got), want)
    assert int(trips) >= 1
    # An all-empty row is already stable: exactly one (no-change) sweep.
    _, trips_empty = fixpoint_iters(arrays, jnp.zeros((1, g.n), jnp.float32))
    assert int(trips_empty) == 1
