"""Two-process `jax.distributed` execution: the multi-host path actually
runs (process_count == 2), the sharded sweep on the global candidate mesh
produces verdicts in both processes, and they match the single-process
result (VERDICT r1 §missing-3 / SURVEY.md §5 distributed-backend
obligation).  CPU emulation: 2 processes × 4 emulated devices each."""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

WORKER = Path(__file__).with_name("_dist_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture(scope="module")
def two_process_results():
    port = _free_port()
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    repo_root = str(WORKER.parent.parent)
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, str(WORKER), str(port), str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            outs.append((p.returncode, out, err))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("two-process run timed out (coordinator deadlock?)")
    results = []
    for rc, out, err in outs:
        if rc != 0:
            tail = "\n".join(err.strip().splitlines()[-12:])
            pytest.fail(f"worker exited {rc}:\n{tail}")
        results.append(json.loads(out.strip().splitlines()[-1]))
    return results


def test_both_processes_joined(two_process_results):
    r0, r1 = two_process_results
    assert r0["process_count"] == r1["process_count"] == 2
    assert {r0["process_index"], r1["process_index"]} == {0, 1}
    assert r0["global_devices"] == r1["global_devices"] == 8


def test_verdicts_agree_across_processes(two_process_results):
    r0, r1 = two_process_results
    assert r0["safe"] == r1["safe"]
    assert r0["broken"] == r1["broken"]


def test_verdict_parity_with_single_process(two_process_results):
    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    r0 = two_process_results[0]
    assert r0["safe"]["intersects"] is True
    assert r0["broken"]["intersects"] is False
    single = solve(majority_fbas(11, broken=True), backend=TpuSweepBackend(batch=64))
    assert single.intersects is False
    # Same deterministic enumeration order ⇒ same first-hit witness pair.
    assert r0["broken"]["q1"] == single.q1
    assert r0["broken"]["q2"] == single.q2
    assert not set(r0["broken"]["q1"]) & set(r0["broken"]["q2"])
    # The sharded run must have counted the full enumeration on the safe net.
    assert r0["safe"]["candidates_checked"] >= 1 << 10

    # Frontier across the two-process mesh: identical on both processes,
    # correct verdict, and the exact oracle minimal-quorum count (108 for
    # hier-4x3) — completeness through the cross-process all_gather path.
    assert r0["frontier"] == two_process_results[1]["frontier"]
    assert r0["frontier"]["intersects"] is True
    assert r0["frontier"]["minimal_quorums"] == 108
