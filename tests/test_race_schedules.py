"""Deterministic race interleavings (ISSUE 3): the auto-router race driven
through its forced orderings via the `_race_sync` hook, every run, in
milliseconds — no wall-clock lottery.

Acceptance: >= 3 forced interleavings with identical verdicts under each
(equal to the sequential race=False chain), on both an intersecting and a
broken topology.  The schedules themselves live in
tools/analyze/schedules.py so `python -m tools.analyze` race runs the same
harness in CI.
"""

import threading

import pytest

from tools.analyze.schedules import (
    SCHEDULES,
    ScheduleError,
    SyncController,
    run_all,
)


@pytest.fixture(scope="module")
def results():
    return run_all()


class TestForcedInterleavings:
    def test_at_least_three_schedules(self):
        assert len(SCHEDULES) >= 3
        assert {
            "sweep_wins_then_oracle_finishes",
            "cancel_during_compile",
            "both_finish_simultaneously",
        } <= set(SCHEDULES)

    def test_identical_verdicts_under_every_interleaving(self, results):
        assert len(results) == len(SCHEDULES) * 2  # x {correct, broken}
        bad = [r for r in results if not r.ok]
        assert not bad, bad
        # Verdict depends on the topology alone, never on the ordering.
        for topology in ("majority9", "majority9-broken"):
            verdicts = {
                r.verdict for r in results if r.topology == topology
            }
            assert len(verdicts) == 1

    def test_sweep_wins_then_oracle_finishes_prefers_oracle(self, results):
        for r in results:
            if r.schedule != "sweep_wins_then_oracle_finishes":
                continue
            # Both engines finished; the driver prefers the oracle's result
            # so witness output matches the sequential path.
            assert r.winner == "oracle"
            assert r.oracle_outcome == "verdict"
            assert r.trace.index("sweep.verdict") < r.trace.index(
                "oracle.returned"
            )

    def test_cancel_during_compile_unwinds_the_sweep(self, results):
        for r in results:
            if r.schedule != "cancel_during_compile":
                continue
            assert r.winner == "oracle"
            # The worker observed its cancel inside the compile phase and
            # unwound AFTER the oracle's verdict.
            assert "sweep.unwound" in r.trace
            assert r.trace.index("oracle.returned") < r.trace.index(
                "sweep.unwound"
            )
            assert "sweep.verdict" not in r.trace

    def test_both_finish_simultaneously_is_deterministic(self, results):
        for r in results:
            if r.schedule != "both_finish_simultaneously":
                continue
            assert r.winner == "oracle"  # deterministic preference
            assert "sweep.verdict" in r.trace

    def test_budget_burn_hands_verdict_to_sweep(self, results):
        for r in results:
            if r.schedule != "budget_burn_then_sweep_verdict":
                continue
            assert r.winner == "sweep"
            assert r.oracle_outcome == "budget_exceeded"
            assert r.trace.index("oracle.returned") < r.trace.index(
                "sweep.verdict"
            )

    def test_no_worker_threads_leak(self, results):
        assert not [
            t for t in threading.enumerate() if t.name == "qi-race-sweep"
        ]


class TestHookHygiene:
    def test_production_hook_restored_after_harness(self, results):
        import quorum_intersection_tpu.backends.auto as auto_mod

        assert auto_mod._race_sync.__name__ == "_race_sync"
        auto_mod._race_sync("no-op")  # and it is still a cheap no-op

    def test_controller_timeout_is_loud(self):
        ctl = SyncController()
        never = threading.Event()
        ctl.hold("point", never)
        import tools.analyze.schedules as sched

        old = sched.WAIT_S
        sched.WAIT_S = 0.05
        try:
            with pytest.raises(ScheduleError, match="held past"):
                ctl("point")
        finally:
            sched.WAIT_S = old

    def test_controller_records_order(self):
        ctl = SyncController()
        ctl("a")
        ctl("b")
        assert ctl.trace == ["a", "b"]
        assert ctl.reached_event("a").is_set()
        assert not ctl.reached_event("c").is_set()
