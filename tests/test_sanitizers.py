"""ASan/UBSan/TSan hygiene of the framework's own C++ (qi_oracle + qi_native).

The reference ships latent UB (the uninitialized-threshold read of SURVEY
§2.3-Q2) and never runs a sanitizer (CMakeLists.txt:1-15).  Here the whole
native surface — JSON parsing, graph build, Tarjan, the B&B search, PageRank
and Graphviz — runs under `-fsanitize=address,undefined` with recovery
disabled, over the golden fixtures AND the hostile-input corpus, so any UB
or memory error aborts the binary and fails the test.  Since ISSUE 3 a
`-fsanitize=thread` variant rides alongside (QI_SANITIZER selects the mode;
'none' makes sanitized builds refuse loudly instead of silently handing
back the plain binary)."""

import subprocess

import pytest

from tests.test_hostile_input import nested_qset_node


@pytest.fixture(scope="module")
def asan_cli():
    from quorum_intersection_tpu.backends.cpp import build_native_cli

    try:
        return str(build_native_cli(sanitize=True))
    except Exception as exc:  # pragma: no cover - g++/libasan missing
        pytest.skip(f"sanitized build unavailable: {exc}")


def run(cli, args, stdin_data=""):
    return subprocess.run(
        [cli, *args], input=stdin_data, capture_output=True, text=True, timeout=300
    )


def assert_no_sanitizer_report(proc):
    for stream in (proc.stderr, proc.stdout):
        assert "ERROR: AddressSanitizer" not in stream
        assert "runtime error:" not in stream  # UBSan
    assert proc.returncode in (0, 1)  # verdict or clean rejection, not abort


GOLDEN = [
    ("correct_trivial.json", 0),
    ("broken_trivial.json", 1),
    ("correct.json", 0),
    ("broken.json", 1),
]


@pytest.mark.parametrize("name,code", GOLDEN)
def test_fixtures_clean_under_sanitizers(asan_cli, ref_fixture, name, code):
    proc = run(asan_cli, ["-v"], ref_fixture(name).read_text())
    assert proc.returncode == code
    assert_no_sanitizer_report(proc)


def test_pagerank_and_graphviz_clean(asan_cli, ref_fixture):
    data = ref_fixture("correct.json").read_text()
    assert_no_sanitizer_report(run(asan_cli, ["-p"], data))
    assert_no_sanitizer_report(run(asan_cli, ["-g"], data))


def test_compat_and_randomized_paths_clean(asan_cli, ref_fixture):
    data = ref_fixture("broken.json").read_text()
    assert_no_sanitizer_report(run(asan_cli, ["--compat", "-v"], data))
    assert_no_sanitizer_report(run(asan_cli, ["--seed", "7", "-t"], data))


class TestSanitizerModes:
    """QI_SANITIZER plumbing (ISSUE 3 satellite): tsan variant builds and
    runs; 'none' and unknown modes fail loudly, never fall back silently."""

    def test_tsan_variant_builds_and_verdicts_match(self, ref_fixture):
        from quorum_intersection_tpu.backends.cpp import build_native_cli

        try:
            cli = str(build_native_cli(sanitize="tsan"))
        except Exception as exc:  # pragma: no cover - toolchain lacks tsan
            pytest.skip(f"tsan build unavailable: {exc}")
        assert "qi_native-tsan-" in cli  # digest-keyed like the asan entry
        for name, code in GOLDEN:
            proc = run(cli, [], ref_fixture(name).read_text())
            assert proc.returncode == code, proc.stderr
            assert "WARNING: ThreadSanitizer" not in proc.stderr

    def test_env_selects_tsan(self, monkeypatch):
        from quorum_intersection_tpu.backends.cpp import sanitizer_mode

        monkeypatch.setenv("QI_SANITIZER", "tsan")
        assert sanitizer_mode() == "tsan"
        monkeypatch.delenv("QI_SANITIZER")
        assert sanitizer_mode() == "asan"  # registry default

    def test_none_mode_refuses_instead_of_falling_back(self, monkeypatch):
        from quorum_intersection_tpu.backends.cpp import build_native_cli

        monkeypatch.setenv("QI_SANITIZER", "none")
        with pytest.raises(RuntimeError, match="QI_SANITIZER=none"):
            build_native_cli(sanitize=True)

    def test_unknown_mode_rejected(self, monkeypatch):
        from quorum_intersection_tpu.backends.cpp import (
            build_native_cli,
            sanitizer_mode,
        )

        monkeypatch.setenv("QI_SANITIZER", "msan")
        with pytest.raises(ValueError, match="msan"):
            sanitizer_mode()
        with pytest.raises(ValueError, match="hwasan"):
            build_native_cli(sanitize="hwasan")


@pytest.mark.parametrize(
    "payload",
    [
        "",  # empty stdin
        "not json",
        "[" * 2000 + "]" * 2000,  # deep arrays (capped parser)
        nested_qset_node(400),  # deep qsets (capped flattener)
        '[{"publicKey": "A", "quorumSet": {"threshold": "' + "9" * 30 + '", "validators": ["A"]}}]',
        '[{"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["\\u0000"]}}]',
        # Null/{} INNER qsets (Q2 at depth > 0): the r5 fuzzer caught the
        # native flattener leaking the root -1 sentinel into the inner
        # pool — slice_unit then read units[-1] (heap-buffer-overflow).
        '[{"publicKey": "A", "quorumSet": {"threshold": 1, '
        '"innerQuorumSets": [{}]}}]',
        '[{"publicKey": "A", "quorumSet": '
        + '{"threshold": 1, "innerQuorumSets": [' * 5 + '{}' + ']}' * 5
        + '}]',
        '[{"publicKey": "A", "quorumSet": {"threshold": 2, "validators": '
        '["A"], "innerQuorumSets": [null, {}]}}]',
    ],
)
def test_hostile_inputs_clean_under_sanitizers(asan_cli, payload):
    proc = run(asan_cli, [], payload)
    assert_no_sanitizer_report(proc)
