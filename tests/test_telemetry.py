"""Unified run-record telemetry (ISSUE 2 tentpole): span nesting, counter
atomicity under the race's two threads, JSONL sink round-trip, stderr
summary format, the CLI ``--metrics-json`` acceptance stream, and the
``QI_LOG_LEVEL`` / ``QI_LOG_JSON`` logging satellites."""

import json
import subprocess
import sys
import threading

import pytest

from quorum_intersection_tpu.fbas.synth import majority_fbas
from quorum_intersection_tpu.utils import telemetry
from quorum_intersection_tpu.utils.telemetry import (
    JsonlSink,
    PromFileSink,
    RunRecord,
)

CLI = [sys.executable, "-m", "quorum_intersection_tpu"]


@pytest.fixture
def fresh_record():
    """A fresh process-wide record (so in-memory assertions see only this
    test's spans/events), restored on exit for later tests."""
    rec = telemetry.reset_run_record()
    yield rec
    telemetry.reset_run_record()


class TestRunRecord:
    def test_span_nesting_parent_ids(self):
        rec = RunRecord()
        with rec.span("outer") as outer:
            with rec.span("inner") as inner:
                with rec.span("leaf") as leaf:
                    pass
        assert outer.parent_id is None
        assert inner.parent_id == outer.span_id
        assert leaf.parent_id == inner.span_id
        names = [sp.name for sp in rec.spans]
        assert names == ["leaf", "inner", "outer"]  # finish order
        assert all(sp.seconds is not None and sp.seconds >= 0 for sp in rec.spans)

    def test_span_attrs_and_set(self):
        rec = RunRecord()
        with rec.span("s", scc=9) as sp:
            sp.set(backend="cpp", winner="oracle")
        assert rec.spans[0].attrs == {
            "scc": 9, "backend": "cpp", "winner": "oracle",
        }

    def test_worker_thread_spans_are_roots(self):
        # Nesting is per-thread: a race worker's spans must not claim the
        # main thread's open span as parent (they run concurrently).
        rec = RunRecord()
        seen = {}

        def worker():
            with rec.span("worker-span") as sp:
                seen["parent"] = sp.parent_id

        with rec.span("main-span"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        assert seen["parent"] is None

    def test_explicit_cross_thread_parent(self):
        rec = RunRecord()
        with rec.span("race") as race_sp:
            with rec.span("sweep", parent_id=race_sp.span_id) as sp:
                pass
        assert sp.parent_id == race_sp.span_id

    def test_counter_atomicity_two_threads(self):
        # The race's two engines increment concurrently; no update may be
        # lost (a bare += on a shared dict would drop some under contention).
        rec = RunRecord()
        n, per = 4, 25_000

        def hammer():
            for _ in range(per):
                rec.add("native.bnb_calls")
                rec.add("sweep.candidates_checked", 2)

        threads = [threading.Thread(target=hammer) for _ in range(n)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert rec.counters["native.bnb_calls"] == n * per
        assert rec.counters["sweep.candidates_checked"] == 2 * n * per

    def test_declared_counters_always_emitted(self):
        # The compile-cache pair is pre-declared: a run that never touches
        # the cache still carries hits=0 / misses=0 in its final lines.
        rec = RunRecord()
        names = {ln["name"] for ln in rec.final_lines() if ln["kind"] == "counter"}
        assert {"compile_cache.hits", "compile_cache.misses"} <= names

    def test_summary_lines_format(self):
        rec = RunRecord()
        with rec.span("phase.search"):
            pass
        rec.add("native.bnb_calls", 7)
        rec.gauge("sweep.candidates_per_sec", 123.4)
        lines = rec.summary_lines()
        assert any(
            l.startswith("[telemetry] span phase.search: ") and l.endswith(" ms")
            for l in lines
        )
        assert "[telemetry] counter native.bnb_calls: 7" in lines
        assert "[telemetry] gauge sweep.candidates_per_sec: 123.4" in lines

    def test_finish_idempotent_and_event_cap(self):
        rec = RunRecord()
        rec.event("e", x=1)
        rec.finish()
        rec.finish()  # second finish must be a no-op, not a double-flush
        assert rec.events[0]["attrs"] == {"x": 1}


class TestSinks:
    def test_jsonl_sink_round_trip(self, tmp_path):
        path = tmp_path / "m.jsonl"
        rec = RunRecord()
        rec.add_sink(JsonlSink(str(path)))
        with rec.span("phase.parse"):
            rec.event("race", winner="oracle")
        rec.add("native.bnb_calls", 3)
        rec.gauge("g", 1.5)
        rec.finish()
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        kinds = [l["kind"] for l in lines]
        assert kinds[0] == "meta"
        assert lines[0]["schema"] == "qi-telemetry/1"
        ev = next(l for l in lines if l["kind"] == "event")
        sp = next(l for l in lines if l["kind"] == "span")
        assert ev["name"] == "race" and ev["attrs"]["winner"] == "oracle"
        assert ev["span_id"] == sp["span_id"]  # event attributed to its span
        assert sp["name"] == "phase.parse" and sp["seconds"] >= 0
        counters = {
            l["name"]: l["value"] for l in lines if l["kind"] == "counter"
        }
        assert counters["native.bnb_calls"] == 3
        gauges = {l["name"]: l["value"] for l in lines if l["kind"] == "gauge"}
        assert gauges["g"] == 1.5

    def test_jsonl_sink_streams_before_finish(self, tmp_path):
        # A crashed run must leave a parseable prefix: span/event lines are
        # written as they happen, not buffered to finish.
        path = tmp_path / "m.jsonl"
        rec = RunRecord()
        rec.add_sink(JsonlSink(str(path)))
        with rec.span("phase.scc"):
            pass
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert any(l["kind"] == "span" for l in lines)

    def test_jsonl_sink_coerces_unserializable_attrs(self, tmp_path):
        path = tmp_path / "m.jsonl"
        rec = RunRecord()
        rec.add_sink(JsonlSink(str(path)))
        rec.event("weird", obj=object(), path=tmp_path)
        rec.finish()
        ev = next(
            json.loads(l) for l in path.read_text().splitlines()
            if json.loads(l)["kind"] == "event"
        )
        assert isinstance(ev["attrs"]["obj"], str)

    def test_prom_textfile_sink(self, tmp_path):
        path = tmp_path / "qi.prom"
        rec = RunRecord()
        rec.add_sink(PromFileSink(str(path)))
        rec.add("sweep.candidates_checked", 42)
        rec.gauge("sweep.candidates_per_sec", 99.5)
        with rec.span("phase.search"):
            pass
        rec.finish()
        text = path.read_text()
        assert "# TYPE qi_sweep_candidates_checked counter" in text
        assert "qi_sweep_candidates_checked 42" in text
        assert "qi_sweep_candidates_per_sec 99.5" in text
        assert "qi_span_phase_search_seconds_count 1" in text

    def test_env_var_sink(self, tmp_path):
        # QI_METRICS_JSON: the zero-plumbing hook CI uses — a subprocess
        # solve must append its stream without any flag.
        path = tmp_path / "env.jsonl"
        proc = subprocess.run(
            CLI + ["--backend", "python"],
            input=json.dumps(majority_fbas(3)),
            capture_output=True, text=True, timeout=120,
            env=_env(QI_METRICS_JSON=str(path)),
        )
        assert proc.returncode == 0
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        assert {l["kind"] for l in lines} >= {"meta", "span", "counter"}


def _env(**extra):
    import os

    env = dict(os.environ)
    env.update(extra)
    return env


class TestCliAcceptance:
    """ISSUE 2 acceptance: one solve with --metrics-json yields spans for
    parse/scc/route/search, a race event, per-window sweep progress with
    candidates/sec, and compile-cache hit/miss counters; metrics_report
    renders the stream without error."""

    def test_auto_solve_stream(self, tmp_path):
        path = tmp_path / "solve.jsonl"
        proc = subprocess.run(
            CLI + ["--metrics-json", str(path)],
            input=json.dumps(majority_fbas(9)),
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        span_names = {l["name"] for l in lines if l["kind"] == "span"}
        assert {"phase.parse", "phase.scc", "route", "phase.search"} <= span_names
        race_events = [
            l for l in lines if l["kind"] == "event" and l["name"] == "race"
        ]
        assert race_events and race_events[0]["attrs"]["winner"] in (
            "oracle", "sweep",
        )
        counters = {l["name"] for l in lines if l["kind"] == "counter"}
        assert {"compile_cache.hits", "compile_cache.misses"} <= counters

    def test_sweep_solve_has_window_progress(self, tmp_path):
        path = tmp_path / "sweep.jsonl"
        proc = subprocess.run(
            CLI + ["--backend", "tpu-sweep", "--metrics-json", str(path)],
            input=json.dumps(majority_fbas(9)),
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        lines = [json.loads(l) for l in path.read_text().splitlines()]
        windows = [
            l for l in lines if l["kind"] == "event" and l["name"] == "sweep.window"
        ]
        assert windows
        attrs = windows[0]["attrs"]
        assert attrs["candidates"] > 0 and "rate" in attrs
        gauges = {l["name"] for l in lines if l["kind"] == "gauge"}
        assert "sweep.candidates_per_sec" in gauges

    def test_metrics_report_renders(self, tmp_path):
        import pathlib

        path = tmp_path / "solve.jsonl"
        proc = subprocess.run(
            CLI + ["--metrics-json", str(path)],
            input=json.dumps(majority_fbas(9)),
            capture_output=True, text=True, timeout=300,
        )
        assert proc.returncode == 0, proc.stderr
        report = subprocess.run(
            [sys.executable,
             str(pathlib.Path(__file__).resolve().parent.parent
                 / "tools" / "metrics_report.py"),
             str(path), "--windows", "4"],
            capture_output=True, text=True, timeout=120,
        )
        assert report.returncode == 0, report.stderr
        assert "per-phase spans" in report.stdout
        assert "phase.search" in report.stdout

    def test_timing_legacy_lines_unchanged_plus_telemetry(self):
        proc = subprocess.run(
            CLI + ["--timing", "--backend", "python"],
            input=json.dumps(majority_fbas(3)),
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0
        err = proc.stderr.splitlines()
        legacy = [l for l in err if l.startswith(("[timing]", "[stats]"))]
        telem = [l for l in err if l.startswith("[telemetry]")]
        assert legacy and telem
        # Legacy block stays contiguous and FIRST (byte-compatible prefix:
        # a consumer parsing the old format sees exactly the old lines
        # before any new ones).
        first_telem = err.index(telem[0])
        assert all(err.index(l) < first_telem for l in legacy)

    def test_prom_flag(self, tmp_path):
        prom = tmp_path / "qi.prom"
        proc = subprocess.run(
            CLI + ["--backend", "python", "--metrics-prom", str(prom)],
            input=json.dumps(majority_fbas(3)),
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0
        assert "# TYPE qi_" in prom.read_text()


class TestPipelineInstrumentation:
    def test_solve_emits_phase_spans_in_process(self, fresh_record):
        from quorum_intersection_tpu.pipeline import solve

        res = solve(majority_fbas(5), backend="python")
        assert res.intersects is True
        names = [sp.name for sp in fresh_record.spans]
        for phase in ("phase.parse", "phase.graph", "phase.scc",
                      "phase.scc_scan", "phase.search"):
            assert phase in names, names
        # Timers facade unchanged: SolveResult.timers still carries the
        # legacy dict the --timing output is built from.
        assert "search" in res.timers

    def test_sweep_feeds_throughput_counter(self, fresh_record):
        from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
        from quorum_intersection_tpu.pipeline import solve

        res = solve(majority_fbas(9), backend=TpuSweepBackend())
        assert res.intersects is True
        assert res.stats["window_candidates_per_sec"] > 0
        assert fresh_record.counters["sweep.candidates_checked"] == 256
        assert fresh_record.counters["sweep.windows_dispatched"] >= 1
        windows = [e for e in fresh_record.events if e["name"] == "sweep.window"]
        assert windows


class TestLoggingSatellites:
    def test_qi_log_level_debug(self):
        # QI_LOG_LEVEL=DEBUG must surface debug narration without -t.
        proc = subprocess.run(
            CLI + ["--backend", "python"],
            input=json.dumps(majority_fbas(3)),
            capture_output=True, text=True, timeout=120,
            env=_env(QI_LOG_LEVEL="DEBUG"),
        )
        assert proc.returncode == 0
        assert "B&B call" in proc.stderr

    def test_qi_log_level_quiet(self):
        proc = subprocess.run(
            CLI + ["--backend", "python"],
            input=json.dumps(majority_fbas(3)),
            capture_output=True, text=True, timeout=120,
            env=_env(QI_LOG_LEVEL="ERROR"),
        )
        assert proc.returncode == 0

    def test_qi_log_json_formatter(self):
        proc = subprocess.run(
            CLI + ["--backend", "python"],
            input=json.dumps(majority_fbas(3)),
            capture_output=True, text=True, timeout=120,
            env=_env(QI_LOG_JSON="1", QI_LOG_LEVEL="DEBUG"),
        )
        assert proc.returncode == 0
        json_logs = []
        for line in proc.stderr.splitlines():
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(obj, dict) and obj.get("kind") == "log":
                json_logs.append(obj)
        assert json_logs, proc.stderr
        assert {"level", "logger", "msg", "t_wall"} <= set(json_logs[0])
