"""qi-mesh suite (ISSUE 19): the multi-host fleet over an adversarial
wire.  Versioned join handshake (typed hello_err on protocol / package /
token skew — never a silently skewed mesh), the bind-address opt-in,
mid-line client-death session hardening, the socket-joined fleet
differential on the vendored fixture pairs (in-process and two-process)
with checker-validated certs including a cross-host composed fragment
through the store gateway, the partition matrix
(suspect → hedge → rejoin-dedup vs suspect → lease-lapse → evict →
journal-ship), pulse-driven elasticity (spawn + drain-retire with oracle
parity), typed adopt_journal rejection, and every ``fleet.{join,lease,
hedge,ship,scale}`` / ``store.fetch`` fault point degrading one rung."""

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from quorum_intersection_tpu import fleet as fleet_mod
from quorum_intersection_tpu.delta import RemoteStoreClient, SharedSccStore
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import churn_trace, majority_fbas
from quorum_intersection_tpu.fleet import (
    FleetEngine,
    JournalUnreadableError,
    MeshHandshakeError,
    SocketWorker,
    StoreGateway,
)
from quorum_intersection_tpu.pipeline import solve
from quorum_intersection_tpu.serve import (
    RequestJournal,
    ServeEngine,
    snapshot_fingerprint,
)
from quorum_intersection_tpu.serve_transport import (
    MESH_PROTOCOL,
    PROTOCOL_SCHEMA,
    SocketServeServer,
    fleet_token_digest,
    package_fingerprint,
)
from quorum_intersection_tpu.utils import faults, telemetry
from tools.check_cert import check_certificate

from tests.conftest import VENDORED_DIR

FIXTURE_PAIRS = [
    ("trivial_correct", True),
    ("trivial_broken", False),
    ("nested_correct", True),
    ("nested_broken", False),
]

REPO_ROOT = Path(__file__).resolve().parents[1]


def fixture_nodes(name):
    return json.loads((VENDORED_DIR / f"{name}.json").read_text())


def fingerprint_of(nodes):
    return snapshot_fingerprint(build_graph(parse_fbas(nodes)))


@pytest.fixture
def rec():
    record = telemetry.reset_run_record()
    faults.clear_plan()
    yield record
    faults.clear_plan()
    telemetry.reset_run_record()


def _wait_counter(record, name, want, timeout=20.0):
    """Poll the run record until counter ``name`` reaches ``want``."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        counters, _ = record.snapshot()
        if counters.get(name, 0) >= want:
            return counters.get(name, 0)
        time.sleep(0.02)
    counters, _ = record.snapshot()
    return counters.get(name, 0)


class _Peer:
    """One in-process 'remote host': a ServeEngine behind the socket
    transport, exactly as ``serve --socket`` runs it on another machine
    (no shared store handed to the engine — fragments only flow over
    the store gateway the join hello advertises)."""

    def __init__(self, journal=None):
        self.engine = ServeEngine(backend="python", journal=journal)
        self.server = None

    def __enter__(self):
        self.engine.start()
        self.server = SocketServeServer(self.engine, port=0, emit_certs=True)
        return self

    def __exit__(self, *exc):
        self.server.stop()
        self.engine.stop(drain=True, timeout=30.0)
        return False

    @property
    def port(self):
        return self.server.port

    @property
    def addr(self):
        return f"127.0.0.1:{self.server.port}"


class _Mesh:
    """Context-managed socket-joined fleet with test-friendly defaults:
    one local worker (w0) plus the given peers (j0..), no auto-respawn
    (evictions stay deterministic), probes only on demand."""

    def __init__(self, tmp_path, joins, n=1, **kwargs):
        kwargs.setdefault("backend", "python")
        kwargs.setdefault("worker_mode", "local")
        kwargs.setdefault("journal_dir", tmp_path / "mesh")
        kwargs.setdefault("probe_interval_s", 30.0)
        kwargs.setdefault("respawn_max", 0)
        self.engine = FleetEngine(n, joins=joins, **kwargs)

    def __enter__(self):
        self.engine.start()
        return self.engine

    def __exit__(self, *exc):
        self.engine.stop(drain=True, timeout=60.0)
        return False


def _routed_to(engine, want, tag, n=7, broken=False):
    """Prefix-search a majority FBAS whose snapshot fingerprint routes
    to worker ``want`` on ``engine``'s ring."""
    for i in range(64):
        cand = majority_fbas(n, broken=broken, prefix=f"{tag}{i}")
        if engine._ring.route(fingerprint_of(cand)) == want:
            return cand
    pytest.skip(f"no prefix routed to {want}")


def _jsonl(conn):
    return conn.makefile("rw", encoding="utf-8")


def _valid_hello(peer="test-peer"):
    return {
        "schema": PROTOCOL_SCHEMA,
        "protocol": MESH_PROTOCOL,
        "fingerprint": package_fingerprint(),
        "token": fleet_token_digest(),
        "peer": peer,
    }


# ---------------------------------------------------------------------------
# versioned join handshake


class TestMeshHandshake:
    def test_valid_hello_answers_hello_ok(self, rec):
        with _Peer() as peer:
            with socket.create_connection(("127.0.0.1", peer.port),
                                          timeout=10.0) as conn:
                fh = _jsonl(conn)
                fh.write(json.dumps({"hello": _valid_hello()}) + "\n")
                fh.flush()
                resp = json.loads(fh.readline())
        ok = resp["hello_ok"]
        assert ok["schema"] == PROTOCOL_SCHEMA
        assert ok["protocol"] == MESH_PROTOCOL
        assert ok["fingerprint"] == package_fingerprint()
        assert ok["ready"] is True and "replay" in ok
        counters, _ = rec.snapshot()
        assert counters.get("serve.hello_rejects", 0) == 0

    @pytest.mark.parametrize("skew,code", [
        ({"schema": "qi-serve/0"}, "protocol_mismatch"),
        ({"protocol": MESH_PROTOCOL + 1}, "protocol_mismatch"),
        ({"fingerprint": "0" * 16}, "fingerprint_mismatch"),
        ({"token": "not-the-digest"}, "bad_token"),
    ])
    def test_skewed_hello_is_typed_reject(self, rec, skew, code):
        """Every mismatch axis gets its own typed hello_err, and the
        session survives the reject (still answers pings) — a reject is
        a protocol answer, not a dropped connection."""
        with _Peer() as peer:
            with socket.create_connection(("127.0.0.1", peer.port),
                                          timeout=10.0) as conn:
                fh = _jsonl(conn)
                hello = {**_valid_hello(), **skew}
                fh.write(json.dumps({"hello": hello}) + "\n")
                fh.flush()
                resp = json.loads(fh.readline())
                assert resp["hello_err"]["code"] == code
                fh.write(json.dumps({"ping": "after-reject"}) + "\n")
                fh.flush()
                assert json.loads(fh.readline())["pong"] == "after-reject"
        counters, _ = rec.snapshot()
        assert counters.get("serve.hello_rejects", 0) == 1

    def test_skewed_join_propagates_never_runs_skewed(self, rec, tmp_path,
                                                      monkeypatch):
        """A fingerprint-skewed peer REFUSES the join with a typed error
        that propagates to the operator — the front door must never
        retry into (or silently run) a skewed mesh."""
        with _Peer() as peer:
            monkeypatch.setattr(fleet_mod, "package_fingerprint",
                                lambda: "f" * 16)
            engine = FleetEngine(
                1, backend="python", worker_mode="local",
                journal_dir=tmp_path / "skew", probe_interval_s=30.0,
                respawn_max=0, joins=[peer.addr],
            )
            try:
                with pytest.raises(MeshHandshakeError) as exc:
                    engine.start()
            finally:
                engine.stop(drain=False, timeout=10.0)
        assert exc.value.reject_code == "fingerprint_mismatch"
        counters, _ = rec.snapshot()
        assert counters.get("fleet.joins", 0) == 0

    def test_join_fault_degrades_to_standalone(self, rec, tmp_path):
        """An injected ``fleet.join`` wire failure (every attempt)
        degrades to a fleet WITHOUT the peer — standalone workers keep
        serving, loudly."""
        faults.install_plan(faults.parse_faults("fleet.join=error@1+"))
        with _Peer() as peer:
            with _Mesh(tmp_path, [peer.addr]) as fleet:
                assert fleet.worker_ids() == ["w0"]
                resp = fleet.submit(
                    fixture_nodes("trivial_correct")).result(timeout=60.0)
                assert resp.intersects is True
        counters, _ = rec.snapshot()
        assert counters.get("fleet.join_errors", 0) == 1
        assert counters.get("fleet.joins", 0) == 0


# ---------------------------------------------------------------------------
# bind-address opt-in (satellite: QI_SERVE_BIND / --bind)


class TestBindAddress:
    def test_default_bind_is_loopback(self, rec, monkeypatch):
        monkeypatch.delenv("QI_SERVE_BIND", raising=False)
        engine = ServeEngine(backend="python")
        engine.start()
        server = SocketServeServer(engine, port=0)
        try:
            assert server.host == "127.0.0.1"
        finally:
            server.stop()
            engine.stop(drain=True, timeout=30.0)

    def test_env_bind_honored_by_serve_and_store(self, rec, tmp_path,
                                                 monkeypatch):
        monkeypatch.setenv("QI_SERVE_BIND", "localhost")
        engine = ServeEngine(backend="python")
        engine.start()
        server = SocketServeServer(engine, port=0)
        gateway = StoreGateway(SharedSccStore(tmp_path / "store"))
        try:
            assert server.host == "localhost"
            assert gateway.host == "localhost"
            with socket.create_connection(("localhost", server.port),
                                          timeout=10.0) as conn:
                fh = _jsonl(conn)
                fh.write(json.dumps({"ping": "bound"}) + "\n")
                fh.flush()
                assert json.loads(fh.readline())["pong"] == "bound"
        finally:
            gateway.stop()
            server.stop()
            engine.stop(drain=True, timeout=30.0)


# ---------------------------------------------------------------------------
# session hardening (satellite: client death mid-line)


class TestSessionHardening:
    def test_client_reset_mid_line_spares_acceptor(self, rec):
        """A client that dies mid-line (RST, torn read) ends ITS session
        with a typed error; the acceptor and later clients are
        untouched."""
        with _Peer() as peer:
            conn = socket.create_connection(("127.0.0.1", peer.port),
                                            timeout=10.0)
            conn.sendall(b'{"request_id": "torn')  # no newline ever comes
            conn.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
            conn.close()  # RST while the session blocks on readline
            assert _wait_counter(rec, "serve.errors", 1) >= 1
            with socket.create_connection(("127.0.0.1", peer.port),
                                          timeout=10.0) as conn2:
                fh = _jsonl(conn2)
                fh.write(json.dumps({"ping": "survivor"}) + "\n")
                fh.flush()
                assert json.loads(fh.readline())["pong"] == "survivor"


# ---------------------------------------------------------------------------
# socket-joined fleet differential (in-process peer)


class TestMeshDifferential:
    @pytest.mark.parametrize("fixture,verdict", FIXTURE_PAIRS)
    def test_joined_fleet_equals_oracle(self, rec, tmp_path, fixture,
                                        verdict):
        nodes = fixture_nodes(fixture)
        with _Peer() as peer:
            with _Mesh(tmp_path, [peer.addr]) as fleet:
                assert fleet.worker_ids() == ["j0", "w0"]
                resp = fleet.submit(nodes).result(timeout=60.0)
        assert resp.intersects is verdict
        assert resp.cert is not None and resp.cert["verdict"] is verdict
        check_certificate(resp.cert, nodes)
        counters, _ = rec.snapshot()
        assert counters.get("fleet.joins", 0) == 1
        assert counters.get("fleet.verdicts", 0) == 1

    def test_remote_routed_request_answers(self, rec, tmp_path):
        """A request whose hash arc belongs to the SOCKET peer solves on
        the remote engine and comes back over the wire, cert intact."""
        with _Peer() as peer:
            with _Mesh(tmp_path, [peer.addr]) as fleet:
                nodes = _routed_to(fleet, "j0", "MR")
                expected = solve(nodes, backend="python").intersects
                resp = fleet.submit(nodes).result(timeout=60.0)
        assert resp.intersects is expected
        check_certificate(resp.cert, nodes)

    def test_cross_host_composed_fragment(self, rec, tmp_path):
        """The cross-host delta story end to end: a fragment SOLVED ON
        THE REMOTE PEER publishes through the store gateway
        (publish-on-solve), and a key-renamed twin routed to the LOCAL
        worker composes its cert from that shipped fragment — zero
        re-solve, and the composed cert passes the unmodified checker."""
        with _Peer() as peer:
            with _Mesh(tmp_path, [peer.addr],
                       store_dir=tmp_path / "store") as fleet:
                base = _routed_to(fleet, "j0", "CH")
                twin = _routed_to(fleet, "w0", "CT")
                first = fleet.submit(base).result(timeout=60.0)
                assert first.intersects is True
                assert _wait_counter(rec, "store.publishes", 1) >= 1
                second = fleet.submit(twin).result(timeout=60.0)
        assert second.intersects is True
        stamp = second.cert["provenance"]["delta"]
        assert stamp["reused_sccs"] == 1
        assert stamp["resolved_sccs"] == 0
        check_certificate(second.cert, twin)


# ---------------------------------------------------------------------------
# partition matrix: suspect → hedge → rejoin vs lease-lapse → evict → ship


class TestPartitionMatrix:
    def test_suspect_hedges_then_rejoin_dedups(self, rec, tmp_path):
        """A suspected worker keeps its arc but its requests HEDGE to the
        next arc owner; when it pongs again it REJOINS, and the in-flight
        hedge deduplicates by wire request id (first answer wins, the
        straggler books fleet.duplicate_responses)."""
        with _Mesh(tmp_path, [], n=2) as fleet:
            nodes = _routed_to(fleet, "w1", "PH")
            expected = solve(nodes, backend="python").intersects
            fleet._suspect_worker("w1", "forced partition (test)")
            resp = fleet.submit(nodes).result(timeout=60.0)
            assert resp.intersects is expected
            assert _wait_counter(rec, "fleet.duplicate_responses", 1) >= 1
            fleet._renew_lease("w1")
            assert fleet.worker_ids() == ["w0", "w1"]
        counters, gauges = rec.snapshot()
        assert counters.get("fleet.suspects", 0) == 1
        assert counters.get("fleet.hedges", 0) >= 1
        assert counters.get("fleet.rejoins", 0) == 1
        assert counters.get("fleet.evictions", 0) == 0
        assert gauges.get("fleet.suspected") == 0

    def test_hedge_fault_degrades_to_single_dispatch(self, rec, tmp_path):
        """An injected ``fleet.hedge`` failure degrades to ONE dispatch
        to the next arc owner — the request still answers, loudly."""
        faults.install_plan(faults.parse_faults("fleet.hedge=error@1+"))
        with _Mesh(tmp_path, [], n=2) as fleet:
            nodes = _routed_to(fleet, "w1", "HF")
            expected = solve(nodes, backend="python").intersects
            fleet._suspect_worker("w1", "forced partition (test)")
            resp = fleet.submit(nodes).result(timeout=60.0)
            assert resp.intersects is expected
        counters, _ = rec.snapshot()
        assert counters.get("fleet.hedge_errors", 0) >= 1
        assert counters.get("fleet.hedges", 0) == 0
        assert counters.get("fleet.duplicate_responses", 0) == 0

    def test_lease_fault_only_delays_eviction(self, rec, tmp_path):
        """An injected ``fleet.lease`` failure leaves a lapsed suspect
        SUSPECT-ONLY (hedged, still serving) — it can only DELAY the
        eviction, which lands as soon as the fault clears."""
        with _Mesh(tmp_path, [], n=2) as fleet:
            fleet._suspect_worker("w1", "forced partition (test)")
            with fleet._lock:
                fleet._leases["w1"] = time.monotonic() - 1.0
            faults.install_plan(faults.parse_faults("fleet.lease=error@1+"))
            fleet._expire_leases()
            assert fleet.worker_ids() == ["w0", "w1"]  # suspect-only
            faults.clear_plan()
            fleet._expire_leases()
            assert fleet.worker_ids() == ["w0"]
            nodes = _routed_to(fleet, "w0", "LE", n=5)
            expected = solve(nodes, backend="python").intersects
            assert fleet.submit(nodes).result(
                timeout=60.0).intersects is expected
        counters, _ = rec.snapshot()
        assert counters.get("fleet.lease_errors", 0) == 1
        assert counters.get("fleet.evictions", 0) == 1

    def test_lease_lapse_evicts_socket_peer_and_ships(self, rec, tmp_path):
        """The full partition death: a socket peer whose lease lapses is
        evicted and its journal SHIPS over the still-open wire — the
        pending entry it never finished replays on the survivor (zero
        lost), its done entries never replay (zero duplicated)."""
        pend = majority_fbas(5, prefix="SHPEND")
        journal_path = tmp_path / "remote.journal"
        with _Peer(journal=journal_path) as peer:
            with _Mesh(tmp_path, [peer.addr]) as fleet:
                done = _routed_to(fleet, "j0", "SD", n=5)
                assert fleet.submit(done).result(
                    timeout=60.0).intersects is True
                # A journaled-but-unfinished entry on the peer's host:
                # appended behind the engine (same O_APPEND file), as a
                # crash between journal-append and solve would leave it.
                extra = RequestJournal(journal_path)
                extra.append_request("mesh-pend", fingerprint_of(pend),
                                     pend, None)
                extra.close()
                fleet._suspect_worker("j0", "forced partition (test)")
                with fleet._lock:
                    fleet._leases["j0"] = time.monotonic() - 1.0
                fleet._expire_leases()
                assert fleet.worker_ids() == ["w0"]
                assert _wait_counter(rec, "fleet.replayed_verdicts", 1) >= 1
        counters, _ = rec.snapshot()
        assert counters.get("fleet.ships", 0) == 1
        assert counters.get("fleet.evictions", 0) == 1
        assert counters.get("fleet.replays", 0) == 1  # pend only, not done
        spool = tmp_path / "mesh" / "shipped" / "j0.shipped.journal"
        assert spool.exists() and spool.stat().st_size > 0

    def test_ship_fault_degrades_to_local_only(self, rec, tmp_path):
        """An injected ``fleet.ship`` failure degrades the eviction to
        local-journal-only failover — loud, never an exception on the
        eviction path."""
        faults.install_plan(faults.parse_faults("fleet.ship=error@1+"))
        with _Peer(journal=tmp_path / "r.journal") as peer:
            with _Mesh(tmp_path, [peer.addr]) as fleet:
                fleet._suspect_worker("j0", "forced partition (test)")
                with fleet._lock:
                    fleet._leases["j0"] = time.monotonic() - 1.0
                fleet._expire_leases()
                assert fleet.worker_ids() == ["w0"]
        counters, _ = rec.snapshot()
        assert counters.get("fleet.ship_errors", 0) == 1
        assert counters.get("fleet.ships", 0) == 0


# ---------------------------------------------------------------------------
# journal shipping (wire protocol)


class TestJournalShipping:
    def test_ship_roundtrip_byte_identical(self, rec, tmp_path):
        """The shipped spool is byte-identical to the peer's journal
        (chunked + length-checked + digest-verified + fsync-before-ack)."""
        journal_path = tmp_path / "peer.journal"
        with _Peer(journal=journal_path) as peer:
            for i in range(3):
                peer.engine.submit(
                    majority_fbas(5, prefix=f"SJ{i}")).result(timeout=60.0)
            worker = SocketWorker("j0", ("127.0.0.1", peer.port),
                                  lambda wid, obj: None)
            try:
                assert worker.wait_ready(timeout=30.0)
                spool = worker.ship_journal(tmp_path / "spool",
                                            timeout=30.0)
                assert spool is not None
                assert spool.read_bytes() == journal_path.read_bytes()
                assert spool.stat().st_size > 0
            finally:
                worker.close(timeout=10.0)
        counters, _ = rec.snapshot()
        assert counters.get("serve.journal_ships", 0) == 1

    def test_ship_without_journal_is_typed_miss(self, rec, tmp_path):
        """A peer running journal-less answers ship_err (no_journal);
        the puller degrades to None, never a bogus empty replay."""
        with _Peer() as peer:  # no --journal
            worker = SocketWorker("j0", ("127.0.0.1", peer.port),
                                  lambda wid, obj: None)
            try:
                assert worker.wait_ready(timeout=30.0)
                assert worker.ship_journal(tmp_path / "spool",
                                           timeout=30.0) is None
            finally:
                worker.close(timeout=10.0)
        counters, _ = rec.snapshot()
        assert counters.get("serve.journal_ships", 0) == 0


# ---------------------------------------------------------------------------
# remote fragment store (qi-store/1 gateway + client)


class TestStoreWire:
    def test_gateway_rejects_bad_token(self, rec, tmp_path):
        gateway = StoreGateway(SharedSccStore(tmp_path / "store"))
        try:
            with socket.create_connection(("127.0.0.1", gateway.port),
                                          timeout=10.0) as conn:
                fh = _jsonl(conn)
                fh.write(json.dumps(
                    {"store_hello": {"schema": "qi-store/1",
                                     "token": "wrong"}}) + "\n")
                fh.flush()
                resp = json.loads(fh.readline())
            assert resp["ok"] is False
        finally:
            gateway.stop()
        counters, _ = rec.snapshot()
        assert counters.get("fleet.store_gateway_rejects", 0) == 1

    def test_client_roundtrip_and_miss(self, rec, tmp_path):
        gateway = StoreGateway(SharedSccStore(tmp_path / "store"))
        client = RemoteStoreClient("127.0.0.1", gateway.port)
        try:
            assert client.fetch("scan", "absent-fp") is None  # clean miss
            payload = {"quorum_local": [1, 2, 3]}
            assert client.publish("scan", "fp-a", payload) is True
            assert client.fetch("scan", "fp-a") == payload
        finally:
            client.close()
            gateway.stop()
        counters, _ = rec.snapshot()
        assert counters.get("store.fetches", 0) == 2
        assert counters.get("store.publishes", 0) == 1
        assert counters.get("store.fetch_errors", 0) == 0

    def test_fetch_fault_degrades_to_local_solve(self, rec, tmp_path):
        faults.install_plan(faults.parse_faults("store.fetch=error@1+"))
        gateway = StoreGateway(SharedSccStore(tmp_path / "store"))
        client = RemoteStoreClient("127.0.0.1", gateway.port,
                                   timeout_s=0.5, retries=1)
        try:
            assert client.fetch("scan", "any-fp") is None
            assert client.publish("scan", "any-fp", {"x": 1}) is False
        finally:
            client.close()
            gateway.stop()
        counters, _ = rec.snapshot()
        assert counters.get("store.fetch_errors", 0) == 2

    def test_dead_gateway_is_a_miss_never_a_raise(self, rec):
        probe = socket.socket()
        probe.bind(("127.0.0.1", 0))
        dead_port = probe.getsockname()[1]
        probe.close()
        client = RemoteStoreClient("127.0.0.1", dead_port,
                                   timeout_s=0.2, retries=1)
        try:
            assert client.fetch("scan", "fp") is None
        finally:
            client.close()
        counters, _ = rec.snapshot()
        assert counters.get("store.fetch_errors", 0) == 1


# ---------------------------------------------------------------------------
# elasticity: the pulse→fleet-size supervisor


class TestElasticity:
    def test_scale_up_then_drain_retire_with_parity(self, rec, tmp_path):
        """One pulse-driven spawn and one drain-retire, oracle parity on
        both sides of each transition (the ISSUE 19 acceptance round)."""
        nodes = majority_fbas(7, prefix="ELA")
        expected = solve(nodes, backend="python").intersects
        with _Mesh(tmp_path, [], n=1) as fleet:
            fleet.scale_up_ms = -1.0  # any queue-wait p99 reads as hot
            assert fleet.scale_tick(force=True) == "up"
            ids = fleet.worker_ids()
            assert len(ids) == 2 and any(w.startswith("e") for w in ids)
            assert fleet.submit(nodes).result(
                timeout=60.0).intersects is expected
            fleet.scale_up_ms = 1e12  # cold again
            fleet.scale_down_ms = 1e12
            assert fleet.scale_tick(force=True) == "down"
            assert fleet.worker_ids() == ["w0"]  # elastic worker retired
            assert fleet.submit(nodes).result(
                timeout=60.0).intersects is expected
        counters, _ = rec.snapshot()
        assert counters.get("fleet.scale_ups", 0) == 1
        assert counters.get("fleet.scale_downs", 0) == 1
        assert counters.get("fleet.errors", 0) == 0

    def test_steady_state_books_a_hold(self, rec, tmp_path):
        with _Mesh(tmp_path, [], n=1) as fleet:
            assert fleet.scale_tick(force=True) is None
        counters, _ = rec.snapshot()
        assert counters.get("fleet.scale_holds", 0) == 1
        assert counters.get("fleet.scale_ups", 0) == 0
        assert counters.get("fleet.scale_downs", 0) == 0

    def test_scale_fault_freezes_fleet_size(self, rec, tmp_path):
        faults.install_plan(faults.parse_faults("fleet.scale=error@1+"))
        with _Mesh(tmp_path, [], n=1) as fleet:
            fleet.scale_up_ms = -1.0  # would scale up if healthy
            assert fleet.scale_tick(force=True) is None
            assert fleet.worker_ids() == ["w0"]  # frozen
        counters, _ = rec.snapshot()
        assert counters.get("fleet.scale_errors", 0) == 1
        assert counters.get("fleet.scale_ups", 0) == 0

    def test_scale_down_never_breaches_min(self, rec, tmp_path):
        with _Mesh(tmp_path, [], n=1) as fleet:
            fleet.scale_down_ms = 1e12  # always reads as cold
            assert fleet.scale_tick(force=True) is None  # live == min
            assert fleet.worker_ids() == ["w0"]


# ---------------------------------------------------------------------------
# adopt_journal: typed rejection (satellite)


class TestAdoptJournal:
    def test_unreadable_path_is_typed(self, rec, tmp_path):
        with _Mesh(tmp_path, [], n=1) as fleet:
            with pytest.raises(JournalUnreadableError) as exc:
                fleet.adopt_journal(tmp_path / "only-on-some-other-host.journal")
        assert exc.value.code == "journal_unreadable"
        assert "ship_journal" in str(exc.value)
        counters, _ = rec.snapshot()
        assert counters.get("fleet.replays", 0) == 0


# ---------------------------------------------------------------------------
# two-process rounds: a REAL serve subprocess joined over the wire


def _spawn_serve(tmp_path, journal_name="remote.journal"):
    """One real ``serve --socket 0`` subprocess; returns (proc, port,
    journal_path).  Stdin stays open — closing it drains and exits."""
    journal_path = tmp_path / journal_name
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONUNBUFFERED"] = "1"
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "quorum_intersection_tpu", "serve",
         "--socket", "0", "--backend", "python", "--emit-certs",
         "--journal", str(journal_path)],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        cwd=str(REPO_ROOT), env=env, text=True,
    )
    port = None
    deadline = time.monotonic() + 120.0
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        obj = json.loads(line)
        if obj.get("kind") == "listening":
            port = int(obj["port"])
            break
    if port is None:
        proc.kill()
        raise AssertionError("serve subprocess never announced its port")
    return proc, port, journal_path


def _stop_serve(proc):
    try:
        if proc.poll() is None:
            proc.stdin.close()
            proc.wait(timeout=30.0)
    except (OSError, subprocess.TimeoutExpired):
        proc.kill()
        proc.wait(timeout=10.0)


class TestTwoProcessMesh:
    def test_cross_host_differential_and_partition(self, rec, tmp_path):
        """The acceptance round, minus the SIGKILL: a real subprocess
        peer joined over TCP answers both fixture pairs oracle-equal
        with checker-validated certs; an injected ``fleet.lease``
        partition only DELAYS its eviction; the cleared eviction ships
        its journal cross-process."""
        proc, port, journal_path = _spawn_serve(tmp_path)
        try:
            with _Mesh(tmp_path, [f"127.0.0.1:{port}"]) as fleet:
                assert fleet.worker_ids() == ["j0", "w0"]
                for fixture, verdict in FIXTURE_PAIRS:
                    nodes = fixture_nodes(fixture)
                    resp = fleet.submit(nodes).result(timeout=120.0)
                    assert resp.intersects is verdict
                    check_certificate(resp.cert, nodes)
                # Partition: suspected + lapsed, but the lease check is
                # faulted — suspect-only, the peer keeps serving hedged.
                fleet._suspect_worker("j0", "forced partition (test)")
                with fleet._lock:
                    fleet._leases["j0"] = time.monotonic() - 1.0
                faults.install_plan(
                    faults.parse_faults("fleet.lease=error@1+"))
                fleet._expire_leases()
                assert "j0" in fleet.worker_ids()
                faults.clear_plan()
                fleet._expire_leases()
                assert fleet.worker_ids() == ["w0"]
        finally:
            _stop_serve(proc)
        counters, _ = rec.snapshot()
        assert counters.get("fleet.verdicts", 0) == len(FIXTURE_PAIRS)
        assert counters.get("fleet.lease_errors", 0) == 1
        assert counters.get("fleet.evictions", 0) == 1
        assert counters.get("fleet.ships", 0) == 1

    @pytest.mark.slow
    def test_sigkill_cross_host_zero_lost(self, rec, tmp_path):
        """The real thing: SIGKILL the remote peer mid-stream — every
        admitted ticket still resolves oracle-equal on the survivor
        (zero lost, zero duplicated), and the dead peer is evicted."""
        trace = churn_trace(majority_fbas(9, prefix="MKK"), 7, seed=6)
        expected = [solve(s, backend="python").intersects for s in trace]
        proc, port, _ = _spawn_serve(tmp_path)
        try:
            fleet = FleetEngine(
                1, backend="python", worker_mode="local",
                journal_dir=tmp_path / "mesh", probe_interval_s=0.2,
                respawn_max=0, joins=[f"127.0.0.1:{port}"],
            )
            fleet.start()
            try:
                tickets = [fleet.submit(s) for s in trace[:5]]
                os.kill(proc.pid, signal.SIGKILL)
                tickets += [fleet.submit(s) for s in trace[5:]]
                got = [t.result(timeout=120.0).intersects for t in tickets]
            finally:
                fleet.stop(drain=True, timeout=60.0)
        finally:
            _stop_serve(proc)
        assert got == expected
        counters, _ = rec.snapshot()
        assert counters.get("fleet.evictions", 0) == 1
        assert counters.get("fleet.errors", 0) == 0
