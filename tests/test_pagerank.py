"""PageRank tests: reference-semantics pinning (C15 quirks), JAX-vs-NumPy
differential agreement, output formatting (C16)."""

import numpy as np
import pytest

from quorum_intersection_tpu.analytics.pagerank import (
    adjacency_counts,
    format_pagerank,
    pagerank,
    pagerank_np,
    sorted_ranks,
)
from quorum_intersection_tpu.fbas.graph import build_graph
from quorum_intersection_tpu.fbas.schema import parse_fbas
from quorum_intersection_tpu.fbas.synth import majority_fbas, random_fbas


def _graph(data):
    return build_graph(parse_fbas(data))


def test_symmetric_graph_uniform_ranks():
    g = _graph(majority_fbas(3))
    ranks = pagerank_np(g)
    assert ranks.shape == (3,)
    np.testing.assert_allclose(ranks, 1 / 3, atol=1e-4)
    np.testing.assert_allclose(ranks.sum(), 1.0, atol=1e-5)


def test_parallel_edges_counted_q7():
    # B listed twice by A → A sends twice the mass per occurrence to B.
    dup = [
        {"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["B", "B", "C"]}},
        {"publicKey": "B", "quorumSet": {"threshold": 1, "validators": ["A"]}},
        {"publicKey": "C", "quorumSet": {"threshold": 1, "validators": ["A"]}},
    ]
    g = _graph(dup)
    a = adjacency_counts(g)
    assert a[0, 1] == 2.0  # multiplicity preserved
    ranks = pagerank_np(g)
    assert ranks[1] > ranks[2]  # B gets 2/3 of A's sends, C gets 1/3


def test_dangling_vertex_leaks_mass():
    # Vertex with no out-edges contributes nothing (cpp:562-563).
    data = [
        {"publicKey": "A", "quorumSet": {"threshold": 1, "validators": ["B"]}},
        {"publicKey": "B", "quorumSet": None},
    ]
    g = _graph(data)
    ranks = pagerank_np(g, max_iterations=50)
    assert ranks.shape == (2,)
    assert np.isfinite(ranks).all()


def test_max_iterations_respected():
    # Directed 5-cycle: mass circulates, so 1 iteration ≠ converged (a
    # complete graph would converge in one step from the e0 init).
    cycle = [
        {"publicKey": f"C{i}", "quorumSet": {"threshold": 1, "validators": [f"C{(i + 1) % 5}"]}}
        for i in range(5)
    ]
    g = _graph(cycle)
    # classic damping mixes fast enough to converge within the cap
    r1 = pagerank_np(g, m=0.15, max_iterations=1)
    r2 = pagerank_np(g, m=0.15, max_iterations=500)
    assert not np.allclose(r1, r2)
    np.testing.assert_allclose(r2, 0.2, atol=1e-2)  # converges to uniform


def test_jax_matches_numpy_model():
    for seed in (0, 1):
        g = _graph(random_fbas(20, seed=seed, null_prob=0.1))
        np.testing.assert_allclose(
            pagerank(g), pagerank_np(g), atol=2e-5
        )


def test_jax_matches_numpy_on_reference_fixture(ref_fixture):
    with open(ref_fixture("correct.json")) as f:
        g = _graph(f.read())
    np.testing.assert_allclose(pagerank(g), pagerank_np(g), atol=2e-5)


def test_sorted_desc_ties_by_label():
    g = _graph(majority_fbas(3))
    ranks = np.array([0.2, 0.6, 0.2], dtype=np.float32)
    out = sorted_ranks(g, ranks)
    assert out[0][0] == "n1"
    assert [label for label, _ in out[1:]] == ["n0", "n2"]  # tie → label asc


def test_format_header_and_lines():
    g = _graph(majority_fbas(3))
    text = format_pagerank(g, pagerank_np(g))
    lines = text.strip().splitlines()
    assert lines[0] == "PageRank:"
    assert all(": " in line for line in lines[1:])


def test_empty_graph():
    g = _graph([])
    assert pagerank_np(g).shape == (0,)
    assert pagerank(g).shape == (0,)


class TestSparseRepresentation:
    """CSR/COO segment-sum path: O(E) memory above DENSE_LIMIT, parity with
    the dense matvec to float32 tolerance (VERDICT r1 §missing-4)."""

    def test_sparse_matches_dense_np(self):
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        g = _graph(stellar_like_fbas(n_watchers=300))
        d = pagerank_np(g, dense=True)
        s = pagerank_np(g, dense=False)
        np.testing.assert_allclose(s, d, rtol=2e-4, atol=2e-6)

    def test_sparse_jax_matches_np(self):
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        g = _graph(stellar_like_fbas(n_watchers=300))
        s_np = pagerank_np(g, dense=False)
        s_jax = pagerank(g, dense=False)
        np.testing.assert_allclose(s_jax, s_np, rtol=2e-4, atol=2e-6)

    def test_auto_selects_sparse_above_limit(self):
        from quorum_intersection_tpu.analytics.pagerank import DENSE_LIMIT, edge_arrays
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        data = stellar_like_fbas(n_watchers=DENSE_LIMIT + 100)
        g = _graph(data)
        assert g.n > DENSE_LIMIT
        src, dst, outdeg = edge_arrays(g)
        # O(E): edge arrays, not an (N, N) matrix
        assert src.shape == dst.shape == (g.n_edges,)
        assert outdeg.sum() == g.n_edges
        r = pagerank_np(g)  # auto → sparse; must converge and normalize
        assert r.shape == (g.n,)
        assert abs(float(r.sum()) - 1.0) < 1e-3

    def test_5k_node_snapshot_scales(self):
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        data = stellar_like_fbas(n_watchers=4800, n_null=100)
        g = _graph(data)
        assert g.n >= 4900
        r_np = pagerank_np(g)
        r_jax = pagerank(g)
        assert r_np.shape == (g.n,)
        np.testing.assert_allclose(r_jax, r_np, rtol=2e-3, atol=2e-6)
        top = sorted_ranks(g, r_np)[0][0]
        assert top.startswith("core")  # the trusted core outranks watchers


class TestAutoEngine:
    """Product-path engine selection (VERDICT r2 §weak-4, r3 latency
    refresh): `--pagerank` routes by measured time-to-result — the device
    power iteration on accelerators only above the measured edge floor
    (below it one dispatch round-trip outweighs the whole NumPy solve) and
    on large CPU graphs, with NumPy as the degradation path."""

    # The package re-exports the `pagerank` function under the same name as
    # the module, so fetch the module itself for attribute monkeypatching.
    import importlib

    pr = importlib.import_module("quorum_intersection_tpu.analytics.pagerank")

    def test_small_graph_on_cpu_uses_numpy(self, monkeypatch):

        monkeypatch.setattr(
            "quorum_intersection_tpu.utils.platform.is_cpu_platform", lambda: True
        )
        ranks, engine = self.pr.pagerank_auto(_graph(majority_fbas(5)))
        assert engine == "numpy"
        np.testing.assert_allclose(ranks, pagerank_np(_graph(majority_fbas(5))))

    def test_accelerator_small_graph_uses_numpy(self, monkeypatch):
        # r3 measured crossover: below ACCEL_MIN_EDGES the dispatch
        # round-trip alone (~77 ms warm on the chip) exceeds the whole
        # NumPy solve (~3 ms on the dump fixture) — time-to-result routing
        # keeps small graphs on the host even on accelerator platforms.
        monkeypatch.setattr(
            "quorum_intersection_tpu.utils.platform.is_cpu_platform", lambda: False
        )
        ranks, engine = self.pr.pagerank_auto(_graph(majority_fbas(5)))
        assert engine == "numpy"

    def test_accelerator_platform_uses_jax_above_edge_floor(self, monkeypatch):

        monkeypatch.setattr(
            "quorum_intersection_tpu.utils.platform.is_cpu_platform", lambda: False
        )
        monkeypatch.setattr(self.pr, "ACCEL_MIN_EDGES", 0)
        g = _graph(majority_fbas(5))
        ranks, engine = self.pr.pagerank_auto(g)
        assert engine == "jax"
        np.testing.assert_allclose(ranks, pagerank_np(g), rtol=1e-4, atol=1e-6)

    def test_large_graph_uses_jax_even_on_cpu(self, monkeypatch):
        from quorum_intersection_tpu.fbas.synth import stellar_like_fbas

        monkeypatch.setattr(
            "quorum_intersection_tpu.utils.platform.is_cpu_platform", lambda: True
        )
        g = _graph(stellar_like_fbas(n_watchers=1100))
        assert g.n > self.pr.JAX_CPU_LIMIT
        ranks, engine = self.pr.pagerank_auto(g)
        assert engine == "jax"
        np.testing.assert_allclose(ranks, pagerank_np(g), rtol=2e-3, atol=2e-6)

    def test_jax_failure_degrades_to_numpy(self, monkeypatch):

        monkeypatch.setattr(
            "quorum_intersection_tpu.utils.platform.is_cpu_platform", lambda: False
        )
        monkeypatch.setattr(self.pr, "ACCEL_MIN_EDGES", 0)  # force the jax route
        def boom(*a, **k):
            raise RuntimeError("device init failed")
        monkeypatch.setattr(self.pr, "pagerank", boom)
        ranks, engine = self.pr.pagerank_auto(_graph(majority_fbas(5)))
        assert engine == "numpy"
        assert ranks.shape == (5,)

    def test_cli_reports_engine_with_timing(self):
        import json
        import subprocess
        import sys

        proc = subprocess.run(
            [sys.executable, "-m", "quorum_intersection_tpu", "-p", "--timing"],
            input=json.dumps(majority_fbas(3)),
            capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == 0
        assert proc.stdout.startswith("PageRank:")
        assert "pagerank_engine:" in proc.stderr
