#!/bin/bash
# Round-4 on-chip measurement sequence — run when the axon tunnel is up.
# Probe first (a down tunnel HANGS, timeout everything); each step records
# to benchmarks/results/ so a mid-sequence tunnel drop keeps the prefix.
set -x
cd "$(dirname "$0")/.."
R=benchmarks/results

# 0. liveness
timeout 100 python -c "import jax; print(jax.devices())" || exit 1

# 1. three-way crossover incl. the frontier win-region rows (scc 28/32)
timeout 1800 python benchmarks/hybrid_crossover.py --large \
    2>&1 | tee "$R/crossover_tpu_r4.txt"

# 2. pop-block scaling on the chip (informs the frontier's default pop)
timeout 1200 python benchmarks/frontier_scaling.py \
    2>&1 | tee "$R/frontier_scaling_tpu_r4.txt"

# 3. wide-sweep ceiling: checkpointed 2^36 with a real SIGKILL + resume
#    (~2 min to the kill, resume runs to completion at ~600M cand/s ≈ 2 min)
timeout 3600 python tools/wide_run.py --bits 36 --kill-after 120 \
    --resume-lo-bits 28 --tag r4

# 4. full bench (the driver also runs this; a builder-recorded copy pins
#    the numbers even if the driver window hits a flake)
timeout 1800 python bench.py 2>/dev/null | tail -1 \
    > "$R/bench_full_r4_onchip.json"

# 5. soak a window on the chip (device engines on real hardware)
timeout 1800 python tools/soak.py --instances 40 --seed 1000 --platform ambient
