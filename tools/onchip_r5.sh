#!/bin/bash
# Round-5 on-chip measurement sequence — run when the axon tunnel is up.
# A down tunnel HANGS rather than errors, so: probe before EVERY step
# (bounding the waste if it drops mid-sequence), run Python unbuffered
# (-u: a SIGTERMed step keeps its completed rows in the tee'd artifact),
# and timeout everything.  Each step records to benchmarks/results/ so a
# drop keeps the prefix.  pipefail: every step ends in a tee/tail pipe,
# so without it a step killed by timeout exits 0 through the pipe and
# tunnel_watch.sh would log "sequence COMPLETE" over truncated artifacts
# (r5 review finding).
set -x
set -o pipefail
cd "$(dirname "$0")/.."
R=benchmarks/results
rc=0

probe() {
    timeout 100 python -c "import jax; print(jax.devices())" || {
        echo "tunnel down before: $1" >&2; exit 1; }
}

# 1. three-way crossover incl. the frontier win-region rows (scc 28/32)
probe crossover
timeout 1800 python -u benchmarks/hybrid_crossover.py --large \
    2>&1 | tee "$R/crossover_tpu_r5.txt" || rc=1

# 2. pop-block scaling on the chip (informs the frontier's default pop)
probe frontier_scaling
timeout 1200 python -u benchmarks/frontier_scaling.py \
    2>&1 | tee "$R/frontier_scaling_tpu_r5.txt" || rc=1

# 3. wide-sweep ceiling: checkpointed 2^36 with a real SIGKILL + resume
#    (~2 min to the kill, resume runs to completion at ~600M cand/s ≈ 2 min)
probe wide_run
timeout 3600 python -u tools/wide_run.py --bits 36 --kill-after 120 \
    --resume-lo-bits 28 --tag r5 || rc=1

# 4. full bench (the driver also runs this; a builder-recorded copy pins
#    the numbers even if the driver window hits a flake)
probe bench
timeout 1800 python -u bench.py 2>/dev/null | tail -1 \
    > "$R/bench_full_r5_onchip.json" || rc=1

# 5. soak a window on the chip (device engines on real hardware); tee'd so
#    per-instance progress/MISMATCH lines survive a mid-window hang (the
#    ledger itself only writes after the full window)
probe soak
timeout 1800 python -u tools/soak.py --instances 40 --seed 1000 --platform ambient \
    2>&1 | tee "$R/soak_tpu_r5.txt" || rc=1

exit $rc
