#!/bin/bash
# Tunnel watcher: probe the axon tunnel every ~9 min; the moment it
# answers, run the full on-chip sequence (tools/onchip.sh) and stop.
# Designed to live in a tmux session for the whole round — r4 lost the
# entire round to a down tunnel, so the watcher removes the human (agent)
# from the loop.  Round and phases parameterize like onchip.sh itself
# (ALL round-named scripts are gone — onchip_r4/r5* collapsed into
# tools/onchip.sh — so the round here is the single name to keep in sync):
#   WATCH_ROUND=r6 WATCH_PHASES="bench packed auto_race" tools/tunnel_watch.sh
# Log: benchmarks/results/tunnel_watch_<round>.log
cd "$(dirname "$0")/.."
ROUND="${WATCH_ROUND:-r6}"
LOG="benchmarks/results/tunnel_watch_${ROUND}.log"
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-11} * 3600 ))

echo "[$(date -u +%FT%TZ)] watcher start (round $ROUND), deadline in ${WATCH_HOURS:-11}h" >> "$LOG"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    if timeout 100 python -c "import jax; print(jax.devices())" >> "$LOG" 2>&1; then
        echo "[$(date -u +%FT%TZ)] TUNNEL UP — launching onchip.sh --round $ROUND" >> "$LOG"
        # shellcheck disable=SC2086 — WATCH_PHASES is a deliberate word list
        bash tools/onchip.sh --round "$ROUND" ${WATCH_PHASES:-} >> "$LOG" 2>&1
        rc=$?
        echo "[$(date -u +%FT%TZ)] onchip.sh exited rc=$rc" >> "$LOG"
        if [ "$rc" -eq 0 ]; then
            echo "[$(date -u +%FT%TZ)] sequence COMPLETE" >> "$LOG"
            exit 0
        fi
        # Mid-sequence drop: completed steps kept their artifacts; keep
        # watching and re-run the whole sequence on the next up-window
        # (steps are idempotent; later runs overwrite with fresher rows).
    else
        echo "[$(date -u +%FT%TZ)] probe: down" >> "$LOG"
    fi
    sleep 540
done
echo "[$(date -u +%FT%TZ)] watcher deadline reached, tunnel never completed a run" >> "$LOG"
