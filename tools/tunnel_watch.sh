#!/bin/bash
# Round-5 tunnel watcher: probe the axon tunnel every ~9 min; the moment it
# answers, run the full on-chip sequence (tools/onchip_r5.sh) and stop.
# Designed to live in a tmux session for the whole round — r4 lost the
# entire round to a down tunnel, so the watcher removes the human (agent)
# from the loop.  Log: benchmarks/results/tunnel_watch_r5.log
cd "$(dirname "$0")/.."
LOG=benchmarks/results/tunnel_watch_r5.log
DEADLINE=$(( $(date +%s) + ${WATCH_HOURS:-11} * 3600 ))

echo "[$(date -u +%FT%TZ)] watcher start, deadline in ${WATCH_HOURS:-11}h" >> "$LOG"
while [ "$(date +%s)" -lt "$DEADLINE" ]; do
    if timeout 100 python -c "import jax; print(jax.devices())" >> "$LOG" 2>&1; then
        echo "[$(date -u +%FT%TZ)] TUNNEL UP — launching onchip_r5.sh" >> "$LOG"
        bash tools/onchip_r5.sh >> "$LOG" 2>&1
        rc=$?
        echo "[$(date -u +%FT%TZ)] onchip_r5.sh exited rc=$rc" >> "$LOG"
        if [ "$rc" -eq 0 ]; then
            echo "[$(date -u +%FT%TZ)] sequence COMPLETE" >> "$LOG"
            exit 0
        fi
        # Mid-sequence drop: completed steps kept their artifacts; keep
        # watching and re-run the whole sequence on the next up-window
        # (steps are idempotent; later runs overwrite with fresher rows).
    else
        echo "[$(date -u +%FT%TZ)] probe: down" >> "$LOG"
    fi
    sleep 540
done
echo "[$(date -u +%FT%TZ)] watcher deadline reached, tunnel never completed a run" >> "$LOG"
