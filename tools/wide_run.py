"""Wide-sweep ceiling exercise: checkpointed 2^N run with a REAL mid-run
SIGKILL + resume (VERDICT r3 §next-6).

Drives the production sweep backend on a safe majority FBAS wide enough
that the two-level (hi|lo) decode runs with hi-bits > 4, in a CHILD
process that is SIGKILLed partway through; the parent then resumes from
the on-disk checkpoint — optionally under a different (batch, lo_bits)
geometry — and records the whole ledger (positions, kill time, resume
position, verdict, rates) to ``benchmarks/results/``.

The CPU emulation sustains ~0.5M cand/s, so the default --bits here would
take days off-chip: run small bits (<= 22) for CPU smoke, the real 36-38
on the chip.

Usage::

    python tools/wide_run.py --bits 20 --kill-after 8 --platform cpu   # smoke
    python tools/wide_run.py --bits 36 --kill-after 120                # chip
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import subprocess
import sys
import time

_REPO = pathlib.Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

RESULTS = _REPO / "benchmarks" / "results"


def child_main(args) -> int:
    """Run the sweep to completion (or until the parent kills us),
    checkpointing to --ckpt; prints one JSON line if it finishes."""
    if args.platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()
    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve
    from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

    ckpt = SweepCheckpoint(pathlib.Path(args.ckpt))
    backend = TpuSweepBackend(
        checkpoint=ckpt,
        lo_bits=args.lo_bits,
        **({"batch": args.batch} if args.batch else {}),
    )
    t0 = time.perf_counter()
    res = solve(majority_fbas(args.bits + 1), backend=backend)
    print(json.dumps({
        "intersects": res.intersects,
        "seconds": round(time.perf_counter() - t0, 2),
        "candidates_checked": res.stats.get("candidates_checked"),
        "candidates_per_sec": round(res.stats.get("candidates_per_sec", 0), 1),
        "steady_rate": res.stats.get("steady_rate"),
        "resumed_from": res.stats.get("resumed_from", 0),
    }), flush=True)
    return 0


def read_ckpt(path: pathlib.Path):
    try:
        return json.loads(path.read_text())
    except Exception:  # noqa: BLE001
        return None


def last_json(out: str) -> dict:
    """Last parseable JSON line of a child's stdout, or an error marker —
    a crashed child (OOM, tunnel drop) must degrade the record, never lose
    the data already gathered before it."""
    for ln in reversed((out or "").strip().splitlines()):
        try:
            return json.loads(ln)
        except json.JSONDecodeError:
            continue
    return {"error": f"child produced no JSON (stdout tail: {(out or '')[-200:]!r})"}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--bits", type=int, default=36,
                        help="enumeration width: sweeps 2^bits of a (bits+1)-node majority")
    parser.add_argument("--kill-after", type=float, default=120.0,
                        help="seconds before SIGKILLing the first attempt")
    parser.add_argument("--lo-bits", type=int, default=30,
                        help="first attempt's two-level split (resume uses --resume-lo-bits)")
    parser.add_argument("--resume-lo-bits", type=int, default=None,
                        help="geometry change on resume (default: lo_bits, i.e. unchanged)")
    parser.add_argument("--batch", type=int, default=None)
    parser.add_argument("--platform", choices=("cpu", "ambient"), default="ambient")
    parser.add_argument("--resume-timeout", type=float, default=3600.0,
                        help="hard deadline for the resume attempt (a hung "
                             "tunnel must degrade the record, not hang it)")
    parser.add_argument("--tag", default="r4", help="results file suffix")
    parser.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    parser.add_argument("--ckpt", default=None, help=argparse.SUPPRESS)
    args = parser.parse_args(argv)

    if args.child:
        return child_main(args)

    if args.bits <= args.lo_bits:
        print(f"--bits {args.bits} must exceed --lo-bits {args.lo_bits} "
              f"(the point is hi-bits > 0)", file=sys.stderr)
        return 2

    RESULTS.mkdir(parents=True, exist_ok=True)
    ckpt_path = RESULTS / f"wide_{args.tag}.ckpt.json"
    ckpt_path.unlink(missing_ok=True)
    record: dict = {
        "bits": args.bits,
        "total_candidates": 1 << args.bits,
        "hi_bits": args.bits - min(args.bits, args.lo_bits),
        "lo_bits": args.lo_bits,
        "platform": args.platform,
    }

    def spawn(lo_bits: int) -> subprocess.Popen:
        cmd = [sys.executable, os.path.abspath(__file__), "--child",
               "--bits", str(args.bits), "--lo-bits", str(lo_bits),
               "--ckpt", str(ckpt_path), "--platform", args.platform]
        if args.batch:
            cmd += ["--batch", str(args.batch)]
        return subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True)

    # Attempt 1: run until the kill deadline, then SIGKILL (a real kill -9,
    # not a simulated exception — the checkpoint on disk is all that
    # survives, exactly the preemption story the ceiling claim needs).
    t0 = time.time()
    proc = spawn(args.lo_bits)
    try:
        out, _ = proc.communicate(timeout=args.kill_after)
        # Finished before the kill: --bits too small for the platform rate.
        record["first_attempt"] = last_json(out)
        record["killed"] = False
        print("first attempt FINISHED before the kill deadline; "
              "no resume exercised — raise --bits or lower --kill-after",
              file=sys.stderr)
    except subprocess.TimeoutExpired:
        proc.send_signal(signal.SIGKILL)
        proc.communicate()
        record["killed"] = True
        record["kill_after_seconds"] = args.kill_after
        ck = read_ckpt(ckpt_path)
        record["checkpoint_at_kill"] = ck
        if not ck or not ck.get("position"):
            print("KILLED but no checkpoint progress was recorded — "
                  "kill window shorter than compile+first record?",
                  file=sys.stderr)
            record["resume"] = "no-checkpoint"
            (RESULTS / f"wide_{args.tag}.json").write_text(json.dumps(record, indent=1))
            return 1

        # Persist what the kill gathered BEFORE risking attempt 2 — a hung
        # resume (tunnel drop mid-collective) must not lose it.
        record["resume"] = "in-progress"
        (RESULTS / f"wide_{args.tag}.json").write_text(json.dumps(record, indent=1))

        # Attempt 2: resume (optionally under a different geometry;
        # lo_bits 0 is a valid all-hi decode, so no falsy-or).
        resume_lo = (
            args.resume_lo_bits if args.resume_lo_bits is not None
            else args.lo_bits
        )
        record["resume_lo_bits"] = resume_lo
        t1 = time.time()
        proc2 = spawn(resume_lo)
        try:
            out, _ = proc2.communicate(timeout=args.resume_timeout)
        except subprocess.TimeoutExpired:
            proc2.send_signal(signal.SIGKILL)
            out, _ = proc2.communicate()
            out = (out or "") + '\n{"error": "resume timed out"}'
        record["resume"] = last_json(out)
        record["resume_wall_seconds"] = round(time.time() - t1, 1)
        resumed_from = ck["position"]
        done = record["resume"].get("candidates_checked")
        if done is not None:
            record["resume_covered_suffix_only"] = (
                done <= (1 << args.bits) - resumed_from
                + (1 << min(args.lo_bits, resume_lo))
            )
    record["wall_seconds"] = round(time.time() - t0, 1)
    out_path = RESULTS / f"wide_{args.tag}.json"
    out_path.write_text(json.dumps(record, indent=1))
    print(json.dumps(record))
    print(f"-> {out_path}", file=sys.stderr)
    ckpt_path.unlink(missing_ok=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
