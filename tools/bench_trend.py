#!/usr/bin/env python
"""Bench-trend regression sentinel (ISSUE 6 tentpole, piece 4).

The repo carries its measured history — driver ``BENCH_r*.json`` wrappers
at the root and builder-recorded rows under ``benchmarks/results/`` — but
until now nothing *watched* it: a PR could halve ``sweep_mfu_pct`` and the
numbers would just sit there.  This tool ingests that history, lines the
runs up in round order, compares the newest run's tracked metrics against
the BEST prior measurement of each, renders the trend as a table
(``tools/metrics_report.py`` formatting), and exits nonzero when a tracked
metric regressed past its tolerance — so the r5 carried numbers
(``pack_fill_pct``, ``sweep_mfu_pct``, ``window_candidates_per_sec``) and
the serving-layer rows (``serve_verdicts_per_sec``, ``serve_p99_ms``,
``serve_cache_hit_pct`` from ``benchmarks/serve.py``, ISSUE 8) are gated,
not just emitted.

Sources, newest-last:

- ``BENCH_r*.json`` — driver wrappers ``{n, cmd, rc, tail, parsed}``; the
  bench summary is ``parsed`` when present, else the last parseable JSON
  line of ``tail``.  A truncated tail or a timed-out run (rc != 0) is
  recorded as a skipped run, never a schema error — killed history is
  expected history.
- ``benchmarks/results/bench_full_r*_onchip.json`` — complete builder-
  recorded bench rows (often the only intact copy of a round the driver
  wrapper truncated).
- ``--telemetry A [B]`` — qi-telemetry/1 JSONL: with two streams, the
  counter/gauge/span deltas via ``metrics_report.diff_streams``; with one,
  its tracked gauges are printed alongside the trend.

Exit codes: 0 clean (or ``--informational``), 1 regression past tolerance,
2 schema error (malformed run file / non-numeric tracked metric) — schema
errors hard-fail even under ``--informational`` (the CI ``bench-trend``
job's contract).

Usage::

    python tools/bench_trend.py                      # committed history
    python tools/bench_trend.py --tolerance 20       # tighter global gate
    python tools/bench_trend.py --tolerance-metric sweep_mfu_pct=10
    python tools/bench_trend.py --informational      # CI: report, exit 0
    python tools/bench_trend.py --telemetry a.jsonl b.jsonl
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path
from typing import Dict, List, Optional, Tuple

try:
    from tools.metrics_report import _table, diff_streams, load_stream
except ImportError:  # executed as a script: tools/ is sys.path[0]
    from metrics_report import _table, diff_streams, load_stream

# Tracked metrics: dotted-flattened key -> direction.  "higher" metrics
# regress by dropping, "lower" (latency) metrics by growing.  Keys absent
# from a run are simply not compared — rounds gain metrics over time.
TRACKED: Dict[str, str] = {
    # headline + sweep throughput
    "value": "higher",
    "sweep_device_cand_per_sec": "higher",
    "wide_sweep_device_cand_per_sec": "higher",
    "sweep_steady_rate": "higher",
    "wide_sweep_steady_rate": "higher",
    "sweep_cand_per_sec": "higher",
    "window_candidates_per_sec": "higher",
    # the r5 carried numbers (ROADMAP on-chip round)
    "sweep_mfu_pct": "higher",
    "wide_sweep_mfu_pct": "higher",
    "pack_fill_pct": "higher",
    # qi-cert coverage metrics (ISSUE 7): the ROADMAP pruning item's wins
    # must land as enumeration-count ratios, so the ledger numbers are
    # gated the moment they exist.  `sweep_enumeration_ratio` =
    # windows_enumerated / window_space (1.0 while the sweep is pure brute
    # force; device-side guard pruning drives it DOWN, and a regression is
    # the ratio creeping back up).  `sweep_windows_pruned` is the
    # pruned-by-guard count itself — higher is better once pruning lands;
    # until then its baseline is 0 and the gate is inert.
    "sweep_windows_enumerated": "lower",
    "sweep_windows_pruned": "higher",
    "sweep_enumeration_ratio": "lower",
    # serving-layer rows (ISSUE 8): benchmarks/serve.py open-loop driver.
    # Throughput and cache efficiency regress by dropping; the tail
    # latency gauge regresses by growing — the pair that catches both a
    # slowed drain loop and a cache keyed wrong (hit_pct collapsing to 0
    # under the same churn trace is a fingerprint bug, not a load change).
    "serve_verdicts_per_sec": "higher",
    "serve_cache_hit_pct": "higher",
    "serve_p50_ms": "lower",
    "serve_p99_ms": "lower",
    # qi-pulse decomposed stage rows (ISSUE 15): the e2e pair above can
    # only say "slower"; these say WHERE — a drain loop that stopped
    # batching shows in queue_wait, a de-optimized engine in solve, and
    # the fleet-MERGED e2e p99 (union of worker histogram buckets, not
    # max of per-worker gauges) is the honest fleet tail.
    "serve_queue_wait_p99_ms": "lower",
    "serve_solve_p99_ms": "lower",
    "fleet_e2e_p99_ms": "lower",
    # qi-delta incremental re-analysis (ISSUE 9): benchmarks/serve.py
    # --churn rows.  `delta_scc_reuse_pct` is per-SCC verdict-store hits
    # as a % of lookups over the churn trace — a collapse to 0 under the
    # same trace means the SCC-local fingerprint went identity-sensitive
    # (cosmetic churn now misses).  `delta_resolve_ratio` is backend
    # solves per trace snapshot — 1.0 means incremental reuse stopped
    # entirely and every step pays the full NP-hard re-solve.
    "delta_scc_reuse_pct": "higher",
    "delta_resolve_ratio": "lower",
    "churn_verdicts_per_sec": "higher",
    # qi-fleet replicated serve tier (ISSUE 11): benchmarks/serve.py
    # --fleet rows.  Aggregate throughput and tail latency at the largest
    # fleet size regress like their serve twins; `fleet_store_hit_pct`
    # is the shared SCC-fragment tier's fleet-wide hit rate — a collapse
    # to 0 under the same churn trace means the read-through tier died
    # (or the fragment keying broke) and every worker silently re-solves
    # alone.
    "fleet_verdicts_per_sec": "higher",
    "fleet_p99_ms": "lower",
    "fleet_store_hit_pct": "higher",
    # qi-mesh socket-joined fleet (ISSUE 19): benchmarks/serve.py --fleet
    # --fleet-join rows.  `fleet_scale_events` counts the elasticity legs
    # that actually fired (forced scale-up + drain-retire ticks; the phase
    # expects exactly one of each, so a drop below 2 means a leg went
    # dead and the fleet no longer resizes under pressure).
    # `fleet_hedge_pct` is hedged dispatches over served verdicts across
    # the phase's fixed partition window — a collapse to 0 means suspected
    # peers no longer hedge (their arc traffic waits on a partitioned
    # socket instead), the exact tail-latency hole hedging exists to
    # close.
    "fleet_scale_events": "higher",
    "fleet_hedge_pct": "higher",
    # qi-query typed queries (ISSUE 12): benchmarks/serve.py --queries
    # rows.  One headline plus a per-kind breakdown, so a regression in
    # ONE resolver (a relaxed enumeration that stopped vectorizing, a
    # whatif frontier that stopped lane-packing) shows up even when the
    # mixed-workload aggregate hides it behind the cheap kinds.
    "query_verdicts_per_sec": "higher",
    "query_intersection_per_sec": "higher",
    "query_relaxed_per_sec": "higher",
    "query_whatif_per_sec": "higher",
    "query_analytics_per_sec": "higher",
    # qi-fuse cross-request pack fusion (ISSUE 16): benchmarks/serve.py
    # --fuse rows.  `sweep_pack_fill_pct` is verdict-bearing lanes over
    # dispatched 128-lane tiles under the mixed fused preset — the MXU
    # utilization fusion exists to raise; `fuse_cross_request_lane_pct`
    # is the share of fused lanes co-packed with a DIFFERENT request — a
    # collapse to 0 means the batch former stopped merging requests (the
    # drain silently fell back to per-request packs); the fused solve p99
    # regresses by growing back toward its unfused twin.
    "sweep_pack_fill_pct": "higher",
    "fuse_cross_request_lane_pct": "higher",
    "fuse_serve_solve_p99_ms": "lower",
    # qi-cost attribution + adaptive fusion (ISSUE 17): benchmarks/serve.py
    # --fuse auto-window arm.  `fuse_auto_window_ms` is the controller's
    # bursty-phase decision — a collapse to 0 means adaptive fusion
    # stopped recognizing a hot queue (every burst drains unfused);
    # `cost_attributed_pct` is attributed lane-windows over dispatched
    # lane-windows — anything under 100 in a fault-free bench means part
    # of the device bill silently lost its owner.
    "fuse_auto_window_ms": "higher",
    "cost_attributed_pct": "higher",
    # qi-sparse bitset-encoding rows (ISSUE 20): benchmarks/sweep_vs_native.py
    # --bitset summary line.  The winning rate regresses by dropping; the
    # measured crossover |scc| regresses by GROWING (the encoding stopped
    # winning smaller SCCs — a kernel or routing regression, since the
    # sparse workloads themselves are pinned presets); bytes streamed per
    # candidate regresses by growing (encoding bloat: the packed operand
    # stopped being 32x denser than the MAC-twin's padded lanes).
    "bitset_candidates_per_sec": "higher",
    "bitset_crossover_scc": "lower",
    "sweep_bytes_per_candidate": "lower",
    # Multichip dryrun rows (MULTICHIP_r*.json driver wrappers): the mesh
    # smoke's sweep-candidate count and frontier device-resident states —
    # a drop means the sharded paths silently shrank their coverage.
    "multichip_sweep_candidates": "higher",
    "multichip_frontier_states": "higher",
    # latency-shaped rows
    "snapshot_verdict_seconds": "lower",
    "verdict_256.auto_seconds": "lower",
    "verdict_1024.auto_seconds": "lower",
    "pagerank_jax_seconds": "lower",
}

# Default tolerance (percent).  Generous by design: the committed history
# spans different chips, tunnel states and bench configs, and the measured
# round-to-round wobble on healthy code reaches tens of percent (r3 vs r5
# onchip rows) — the default gate exists to catch the order-of-magnitude
# cliff a broken kernel or mis-routed backend produces, while --tolerance /
# --tolerance-metric tighten specific numbers once a stable rig exists.
DEFAULT_TOLERANCE_PCT = 50.0

# The gauges a qi-telemetry stream contributes to the trend view.
TELEMETRY_GAUGES = (
    "sweep.candidates_per_sec",
    "sweep.pack_fill_pct",
    "sweep.xla_compile_seconds",
    "cert.enumeration_ratio",
    "serve.p50_ms",
    "serve.p99_ms",
    "serve.queue_depth",
    "serve.bench_verdicts_per_sec",
    "delta.scc_reuse_pct",
    "delta.store_size",
    "delta.bench_reuse_pct",
    "fleet.workers_live",
    "fleet.store_hit_pct",
    "fleet.p99_ms",
    "fleet.e2e_p99_ms",
    "fleet.bench_verdicts_per_sec",
    "fuse.fill_pct",
    "fuse.bench_fill_pct",
    "fuse.bench_cross_request_lane_pct",
    "serve.fuse_window_ms",
    "fuse.bench_auto_window_ms",
    "cost.bench_attributed_pct",
    "slo.burning",
    "fleet.cost_tenants",
)


class SchemaError(ValueError):
    """A run file that exists but cannot be trusted: hard-fail material."""


def _flatten(obj: object, prefix: str = "") -> Dict[str, float]:
    """Numeric leaves of a nested dict, dotted keys; bools excluded."""
    out: Dict[str, float] = {}
    if isinstance(obj, dict):
        for key, value in obj.items():
            path = f"{prefix}.{key}" if prefix else str(key)
            if isinstance(value, bool):
                continue
            if isinstance(value, (int, float)):
                out[path] = float(value)
            elif isinstance(value, dict):
                out.update(_flatten(value, path))
    return out


def _last_json_line(text: str) -> Optional[dict]:
    """Scan backwards for the last complete JSON object line (a SIGKILL or
    a log tail can corrupt the literal last line without invalidating the
    rows before it — the bench driver's own salvage discipline)."""
    for line in reversed([ln for ln in (text or "").splitlines() if ln.strip()]):
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def load_bench_wrapper(path: Path) -> Tuple[Optional[dict], str]:
    """One ``BENCH_r*.json`` driver wrapper -> (raw bench row, note)."""
    try:
        wrapper = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        raise SchemaError(f"{path.name}: unreadable run wrapper: {exc}")
    if not isinstance(wrapper, dict) or "tail" not in wrapper:
        raise SchemaError(
            f"{path.name}: expected a driver wrapper with a 'tail' field"
        )
    row = wrapper.get("parsed")
    if not isinstance(row, dict):
        if wrapper.get("rc") not in (0, None):
            # A timed-out/killed round: whatever JSON its tail happens to
            # end in (a log line, a partial row) is not that round's bench
            # result — skipping is the documented contract.  A driver-
            # recorded `parsed` row (above) is still trusted.
            return None, (
                f"skipped (rc={wrapper.get('rc')}: run failed; tail not "
                f"trusted as a bench row)"
            )
        row = _last_json_line(str(wrapper.get("tail", "")))
        if row is not None and not ({"metric", "value"} & row.keys()):
            # A parseable line that is not a bench headline (QI_LOG_JSON
            # log line, intermediate phase row) must not become a baseline.
            row = None
    if row is None:
        return None, (
            f"skipped (rc={wrapper.get('rc')}: no parseable bench row in tail"
            f" — truncated or timed-out run)"
        )
    return row, "ok"


_MULTICHIP_RE = re.compile(
    r"dryrun_multichip OK: (\d+)-device mesh, (\d+) (?:sweep )?candidates"
)
_MULTICHIP_STATES_RE = re.compile(r"\((\d+) device-resident states\)")


def load_multichip_wrapper(path: Path) -> Tuple[Optional[dict], str]:
    """One ``MULTICHIP_r*.json`` dryrun wrapper -> (bench row, note).

    The dryrun prints a human OK line, not a JSON row, so the metrics are
    lifted by regex from the tail: mesh size, sweep-candidate count and
    (when the frontier path ran) device-resident states.  A failed or
    skipped round — or a tail whose OK line was buried under runtime
    noise (r01's AOT loader spew) — is a skipped run, never a schema
    error.  The device string is ``dryrun-mesh-N`` so these rows only
    ever baseline against other dryruns of the same mesh size, never
    against real bench rows.
    """
    try:
        wrapper = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        raise SchemaError(f"{path.name}: unreadable multichip wrapper: {exc}")
    if not isinstance(wrapper, dict) or "tail" not in wrapper:
        raise SchemaError(
            f"{path.name}: expected a driver wrapper with a 'tail' field"
        )
    if wrapper.get("rc") not in (0, None) or wrapper.get("skipped"):
        return None, (
            f"skipped (rc={wrapper.get('rc')}, "
            f"skipped={bool(wrapper.get('skipped'))}: dryrun did not "
            f"complete)"
        )
    tail = str(wrapper.get("tail", ""))
    m = _MULTICHIP_RE.search(tail)
    if m is None:
        return None, "skipped (no dryrun_multichip OK line in tail)"
    n_devices = int(m.group(1))
    row: dict = {
        "multichip_devices": n_devices,
        "multichip_sweep_candidates": int(m.group(2)),
        "device": f"dryrun-mesh-{n_devices}",
    }
    states = _MULTICHIP_STATES_RE.search(tail)
    if states is not None:
        row["multichip_frontier_states"] = int(states.group(1))
    return row, "ok"


def load_result_row(path: Path) -> dict:
    """One complete bench row under benchmarks/results/."""
    try:
        row = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError) as exc:
        raise SchemaError(f"{path.name}: unreadable bench row: {exc}")
    if not isinstance(row, dict):
        raise SchemaError(f"{path.name}: bench row is not a JSON object")
    return row


_ROUND_RE = re.compile(r"r(\d+)")


def _round_of(name: str) -> int:
    m = _ROUND_RE.search(name)
    return int(m.group(1)) if m else -1


def load_history(
    repo: Path,
) -> Tuple[List[Tuple[str, Dict[str, float], str]], List[str]]:
    """All runs in round order (builder-recorded onchip rows after the same
    round's driver wrapper — they are the more complete record).  Returns
    ``(runs, notes)``; each run is ``(name, flat metrics, device string)``
    and only parseable rows are included."""
    entries: List[Tuple[Tuple[int, int], str, Optional[dict], str]] = []
    for path in sorted(repo.glob("BENCH_r*.json")):
        row, note = load_bench_wrapper(path)
        entries.append(((_round_of(path.name), 0), path.name, row, note))
    for path in sorted(repo.glob("MULTICHIP_r*.json")):
        row, note = load_multichip_wrapper(path)
        entries.append(((_round_of(path.name), 2), path.name, row, note))
    results = repo / "benchmarks" / "results"
    if results.is_dir():
        for path in sorted(results.glob("bench_full_r*_onchip.json")):
            row = load_result_row(path)
            entries.append(((_round_of(path.name), 1), path.name, row, "ok"))
    entries.sort(key=lambda e: e[0])
    runs: List[Tuple[str, Dict[str, float], str]] = []
    notes: List[str] = []
    for _, name, row, note in entries:
        if row is None:
            notes.append(f"{name}: {note}")
        else:
            runs.append((name, _flatten(row), str(row.get("device", "?"))))
    return runs, notes


def trend(
    runs: List[Tuple[str, Dict[str, float], str]],
    tolerances: Dict[str, float],
    default_tol: float,
) -> Tuple[List[List[str]], List[str]]:
    """Trend rows (latest vs best prior per tracked metric) + regressions.

    Device-partitioned, the calibration module's discipline: the latest run
    compares only against prior runs recorded on the SAME device string — a
    cpu-fallback round's 21 ms snapshot verdict is not a baseline a
    tunneled-chip round can regress against (they measure different
    machines, and the committed history contains exactly that pair).
    """
    if not runs:
        return [], []
    latest_name, latest, latest_device = runs[-1]
    prior_runs = [
        (name, m) for name, m, device in runs[:-1] if device == latest_device
    ]
    rows: List[List[str]] = []
    regressions: List[str] = []
    for metric, direction in TRACKED.items():
        cur = latest.get(metric)
        prior = [
            (name, m[metric]) for name, m in prior_runs if metric in m
        ]
        if cur is None and not prior:
            continue
        if cur is None:
            rows.append([metric, "-", "-", "-", "absent in latest"])
            continue
        if not prior:
            rows.append([metric, "-", f"{cur:.6g}", "-", "new"])
            continue
        best_name, best = (
            max(prior, key=lambda p: p[1]) if direction == "higher"
            else min(prior, key=lambda p: p[1])
        )
        if best == 0:
            rows.append([metric, f"{best:.6g}", f"{cur:.6g}", "-", "ok"])
            continue
        delta_pct = (cur - best) / abs(best) * 100.0
        tol = tolerances.get(metric, default_tol)
        regressed = (
            delta_pct < -tol if direction == "higher" else delta_pct > tol
        )
        status = f"REGRESSED (> {tol:g}% vs {best_name})" if regressed else "ok"
        if regressed:
            regressions.append(
                f"{metric}: {cur:.6g} vs best {best:.6g} ({best_name}), "
                f"delta {delta_pct:+.1f}% past the {tol:g}% tolerance"
            )
        rows.append([
            metric, f"{best:.6g}", f"{cur:.6g}", f"{delta_pct:+.1f}%", status,
        ])
    return rows, regressions


def telemetry_section(paths: List[str]) -> Tuple[str, int]:
    """Render the telemetry half: one stream -> tracked gauges; two ->
    the metrics_report diff table.  Returns (text, schema_rc)."""
    try:
        streams = [load_stream(p) for p in paths]
    except OSError as exc:
        return f"telemetry: cannot read stream: {exc}", 2
    if len(streams) == 1:
        data = streams[0]
        rows = [
            [name, f"{data['gauges'][name]}"]
            for name in TELEMETRY_GAUGES if name in data["gauges"]
        ]
        body = _table(rows, ["gauge", "value"]) if rows else "(no tracked gauges)"
        return f"== tier-1 telemetry: {paths[0]} ==\n{body}", 0
    rows = diff_streams(streams[0], streams[1])
    body = _table(rows, ["name", "kind", "a", "b", "delta", "delta_pct"]) \
        if rows else "(nothing to compare)"
    return f"== telemetry diff: {paths[0]} -> {paths[1]} ==\n{body}", 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--repo", default=None, metavar="DIR",
                        help="repository root holding BENCH_r*.json "
                             "(default: this file's repo)")
    parser.add_argument("--tolerance", type=float,
                        default=DEFAULT_TOLERANCE_PCT, metavar="PCT",
                        help="global regression tolerance in percent "
                             f"(default {DEFAULT_TOLERANCE_PCT:g})")
    parser.add_argument("--tolerance-metric", action="append", default=[],
                        metavar="NAME=PCT",
                        help="per-metric tolerance override (repeatable)")
    parser.add_argument("--informational", action="store_true",
                        help="report regressions but exit 0 for them "
                             "(schema errors still exit 2 — the CI mode)")
    parser.add_argument("--telemetry", nargs="+", default=None,
                        metavar="JSONL",
                        help="also ingest one or two qi-telemetry/1 streams "
                             "(two: rendered as a delta table)")
    args = parser.parse_args(argv)

    repo = Path(args.repo) if args.repo else Path(__file__).resolve().parent.parent
    tolerances: Dict[str, float] = {}
    for spec in args.tolerance_metric:
        name, _, pct = spec.partition("=")
        try:
            tolerances[name.strip()] = float(pct)
        except ValueError:
            print(f"malformed --tolerance-metric {spec!r}", file=sys.stderr)
            return 2

    try:
        runs, notes = load_history(repo)
    except SchemaError as exc:
        print(f"schema error: {exc}", file=sys.stderr)
        return 2

    print(f"bench-trend: {len(runs)} parseable run(s) under {repo}")
    for note in notes:
        print(f"  note: {note}")
    # The multichip dryrun family trends in its OWN lane: its rows carry
    # none of the bench metrics, so letting a MULTICHIP round become "the
    # latest run" would silently un-gate every real bench number.
    multichip = [r for r in runs if r[2].startswith("dryrun-mesh-")]
    bench = [r for r in runs if not r[2].startswith("dryrun-mesh-")]
    rc = 0
    regressions: List[str] = []
    if not runs:
        print("no bench history to compare — nothing gated")
    for label, family in (("bench", bench), ("multichip", multichip)):
        if not family:
            continue
        print(f"latest {label} run: {family[-1][0]} "
              f"(device: {family[-1][2]})")
        rows, regs = trend(family, tolerances, args.tolerance)
        regressions.extend(regs)
        if rows:
            print(_table(
                rows, ["metric", "best_prior", "latest", "delta", "status"]
            ))
        else:
            print("(no tracked metrics present)")
    if regressions:
        for reg in regressions:
            print(f"REGRESSION: {reg}", file=sys.stderr)
        rc = 0 if args.informational else 1

    if args.telemetry:
        text, sry = telemetry_section(args.telemetry[:2])
        print(text)
        if sry:
            return sry
    return rc


if __name__ == "__main__":
    sys.exit(main())
