#!/bin/bash
# Parameterized on-chip measurement driver — replaces the copy-pasted
# onchip_r5.sh / onchip_r5b.sh / onchip_r5c.sh (ISSUE 6 satellite) and the
# leftover onchip_r4.sh (ISSUE 7 satellite: the r4 sequence IS the default
# phase list, so `tools/onchip.sh --round r4` reproduces it exactly): one
# script, round + phase flags, same per-step discipline the r5 scripts
# converged on:
#   - a down tunnel HANGS rather than errors, so probe before EVERY phase
#     (bounding the waste if it drops mid-sequence);
#   - run Python unbuffered (-u: a SIGTERMed step keeps its completed rows
#     in the tee'd artifact) with a timeout on everything;
#   - pipefail, so a step killed mid-pipe fails the script instead of
#     exiting 0 through tee (r5 review finding — tunnel_watch.sh keys
#     "sequence COMPLETE" off rc=0).
# New in this round: the whole sequence exports QI_METRICS_JSON,
# QI_TRACE_OUT and QI_FLIGHT_RECORDER (docs/OBSERVABILITY.md), so the next
# measurement round lands a Perfetto timeline and crash forensics alongside
# its bench rows — and `tools/bench_trend.py` gates the rows afterwards.
#
# Usage: tools/onchip.sh --round rN [phase ...]
#   default phases:   crossover frontier_scaling wide_run bench soak
#   extra phases:     sweep_vs_native wide_kill crossover_pop2048 scc36
#                     auto_race packed fuse sparse
# Examples (the r4/r5 sequences, reproduced):
#   tools/onchip.sh --round r4                                  # = onchip_r4.sh
#   tools/onchip.sh --round r5                                  # = onchip_r5.sh
#   tools/onchip.sh --round r5 sweep_vs_native wide_kill crossover_pop2048
#                                                               # = onchip_r5b.sh
#   tools/onchip.sh --round r5 scc36                            # = onchip_r5c.sh
# Round names parameterize everywhere: the tunnel watcher launches this
# script with WATCH_ROUND (tools/tunnel_watch.sh) — keep the two in sync
# by passing the SAME rN to both.
set -x
set -o pipefail
cd "$(dirname "$0")/.."

ROUND=""
PHASES=()
while [ $# -gt 0 ]; do
    case "$1" in
        --round)
            [ $# -ge 2 ] || { echo "--round needs a value" >&2; exit 2; }
            ROUND="$2"; shift 2 ;;
        -h|--help) grep '^#' "$0" | sed 's/^# \{0,1\}//'; exit 0 ;;
        *) PHASES+=("$1"); shift ;;
    esac
done
if [ -z "$ROUND" ]; then
    echo "usage: tools/onchip.sh --round rN [phase ...]" >&2
    exit 2
fi
[ ${#PHASES[@]} -eq 0 ] && PHASES=(crossover frontier_scaling wide_run bench soak)

R=benchmarks/results
# One observability stream per sequence: every phase (and its subprocess
# children, via the env hooks) appends spans/events here; traces open in
# ui.perfetto.dev as one timeline per sequence.
export QI_METRICS_JSON="$R/metrics_${ROUND}_onchip.jsonl"
export QI_TRACE_OUT="$R/trace_${ROUND}_onchip.json"
export QI_FLIGHT_RECORDER="$R/flight_${ROUND}_onchip.json"

probe() {
    timeout 100 python -c "import jax; print(jax.devices())" || {
        echo "tunnel down before: $1" >&2; exit 1; }
}

run_phase() {
    case "$1" in
        crossover)
            # three-way crossover incl. the frontier win-region rows
            timeout 1800 python -u benchmarks/hybrid_crossover.py --large \
                2>&1 | tee "$R/crossover_tpu_${ROUND}.txt" ;;
        crossover_pop2048)
            # frontier win-region rows under pop=2048 (the frontier_scaling
            # sweet spot) — appended to the SAME round artifact so
            # calibration takes the completed ratio over an earlier estimate
            timeout 1800 python -u benchmarks/hybrid_crossover.py --large-only --pop 2048 \
                2>&1 | tee -a "$R/crossover_tpu_${ROUND}.txt" ;;
        frontier_scaling)
            # pop-block scaling on the chip (informs the frontier's default pop)
            timeout 1200 python -u benchmarks/frontier_scaling.py \
                2>&1 | tee "$R/frontier_scaling_tpu_${ROUND}.txt" ;;
        wide_run)
            # wide-sweep ceiling: checkpointed 2^36 with a real SIGKILL + resume
            timeout 3600 python -u tools/wide_run.py --bits 36 --kill-after 120 \
                --resume-lo-bits 28 --tag "$ROUND" ;;
        wide_kill)
            # kill EARLY enough to really fire (the r5 2^36 run finished in
            # 92 s, before the 120 s kill — VERDICT §next-6 wants a real
            # on-chip SIGKILL + resume)
            timeout 1800 python -u tools/wide_run.py --bits 36 --kill-after 45 \
                --resume-lo-bits 28 --tag "${ROUND}kill" ;;
        bench)
            # full bench (the driver also runs this; a builder-recorded copy
            # pins the numbers even if the driver window hits a flake)
            timeout 1800 python -u bench.py 2>/dev/null | tail -1 \
                > "$R/bench_full_${ROUND}_onchip.json" ;;
        soak)
            # soak a window on the chip (device engines on real hardware)
            timeout 1800 python -u tools/soak.py --instances 40 --seed 1000 \
                --platform ambient 2>&1 | tee "$R/soak_tpu_${ROUND}.txt" ;;
        sweep_vs_native)
            # the artifact that raises auto's accelerator sweep limit
            # (backends/calibration.py sweep window)
            timeout 3600 python -u benchmarks/sweep_vs_native.py --native-cap 900 \
                2>&1 | tee "$R/sweep_vs_native_tpu_${ROUND}.txt" ;;
        scc36)
            # try to complete the native oracle at scc 36 so the sweep
            # window's largest win is MEASURED, not estimated — appended to
            # the round artifact (a new file name would tie on round rank
            # and be ignored by calibration).  Budget ~2x the call-count
            # model: it UNDERESTIMATES above scc 32 (r5 measured reality);
            # even a failed run still measures a floor.
            timeout 7200 python -u benchmarks/sweep_vs_native.py --scc 36 --native-cap 4000 \
                2>&1 | tee -a "$R/sweep_vs_native_tpu_${ROUND}.txt" ;;
        auto_race)
            # ROADMAP carried debt: the row that lands calibration.sweep_warm_ratio
            timeout 1800 python -u benchmarks/auto_race.py --real --warm-start \
                --metrics-json "$QI_METRICS_JSON" \
                2>&1 | tee "$R/auto_race_tpu_${ROUND}.txt" ;;
        packed)
            # ROADMAP carried debt: the measured packed win rows
            # (calibration.pack_win_max_scc + the packed sweep_mfu_pct row)
            timeout 3600 python -u benchmarks/sweep_vs_native.py --packed \
                --metrics-json "$QI_METRICS_JSON" \
                2>&1 | tee "$R/sweep_vs_native_packed_tpu_${ROUND}.txt" ;;
        fuse)
            # qi-fuse on real hardware: the fused vs unfused serve drain
            # head-to-head (cross-request lanes, tile fill, byte-parity
            # certs all gated by the driver itself) — on-chip is where the
            # fused-tile win is a real MXU number, not CPU emulation.
            # QI_SLO arms the qi-cost burn plane so the auto-window arm
            # exercises the full closed loop (decision events + burn
            # clamping) against real device latencies; the loose bound
            # never burns on a healthy chip.
            timeout 1800 env QI_SLO="serve_e2e_p99_ms<600000" \
                python -u benchmarks/serve.py --fuse \
                --backend tpu \
                2>&1 | tee "$R/serve_fuse_tpu_${ROUND}.txt" ;;
        sparse)
            # qi-sparse on real hardware: bitset-vs-dense twin rows on the
            # sparse presets.  The artifact name is distinct from the
            # sweep_vs_native phase's (calibration's bitset parser only
            # reads files that actually carry bitset rows, so the split
            # keeps round-rank ties away from the sweep-window gate) and
            # lands the TPU win region for backends/calibration.py's
            # bitset gate — until it exists, auto routes bitset only on
            # the CPU region measured in sweep_vs_native_cpu_r6.txt.
            timeout 3600 python -u benchmarks/sweep_vs_native.py --bitset \
                --metrics-json "$QI_METRICS_JSON" \
                2>&1 | tee "$R/sweep_vs_native_bitset_tpu_${ROUND}.txt" ;;
        *)
            echo "unknown phase: $1" >&2; return 2 ;;
    esac
}

rc=0
for ph in "${PHASES[@]}"; do
    probe "$ph"
    run_phase "$ph" || rc=1
done

# Trend gate over the freshly landed rows (informational here — the row is
# already recorded; CI's bench-trend job holds the line on schema).
python tools/bench_trend.py --informational || rc=1

exit $rc
