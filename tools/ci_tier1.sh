#!/usr/bin/env bash
# Tier-1 verification gate — the exact command ROADMAP.md pins, wrapped so
# every PR runs the same gate locally and in CI (.github/workflows/tier1.yml).
#
# Contract (keep in sync with ROADMAP.md "Tier-1 verify"):
#   - CPU platform only (JAX_PLATFORMS=cpu): no chip, no tunnel;
#   - not-slow marker selection, collection errors tolerated per-file;
#   - pipefail + a DOTS_PASSED count parsed from the progress dots, so the
#     driver can compare pass totals across runs even when the exit code
#     alone would hide a shrinking suite;
#   - hard timeout (870 s) with SIGKILL escalation;
#   - run-record telemetry (docs/OBSERVABILITY.md) streamed to
#     $TIER1_METRICS (default /tmp/_t1_metrics.jsonl): every in-process
#     solve and every CLI subprocess the suite spawns appends to one
#     qi-telemetry/1 JSONL file, so a perf regression spotted in CI is
#     inspectable (tools/metrics_report.py) instead of anecdotal;
#   - the static-analysis suite (docs/STATIC_ANALYSIS.md) runs after the
#     tests: `python -m tools.analyze` must exit clean — ALL SIX passes
#     (qi-lint, qi-surface contract/registry drift incl. the committed
#     surface_inventory.json staleness gate, qi-locks lock-order/lockset,
#     qi-wire producer⊇consumer, typing ratchet, race schedules + tsan) —
#     and its findings stream to $TIER1_ANALYZE in the same
#     qi-telemetry/1 shape;
#   - a qi-cert gate (ISSUE 7): CLI-written verdict certificates for the
#     vendored fixture pairs re-validated by the independent stdlib
#     checker tools/check_cert.py ($TIER1_CERTS holds the artifacts);
#   - a chaos-soak smoke (docs/ROBUSTNESS.md) runs last: a small fixed-seed
#     window of `tools/soak.py --chaos` — every injected fault schedule
#     must leave the verdict equal to the fault-free sequential chain or
#     fail with a typed error.  Any gate failing fails the script;
#   - a serving-layer smoke (ISSUE 8, README §Serving): a short open-loop
#     `benchmarks/serve.py --quick` run (every served verdict compared to
#     the one-shot oracle, any silent drop = exit 1) plus a chaos variant
#     `tools/soak.py --serve --chaos` covering the serve.* fault points
#     and one kill-and-replay journal round.
#
# Usage: tools/ci_tier1.sh [extra pytest args...]
set -o pipefail

cd "$(dirname "$0")/.."

LOG="${TIER1_LOG:-/tmp/_t1.log}"
METRICS="${TIER1_METRICS:-/tmp/_t1_metrics.jsonl}"
TRACE="${TIER1_TRACE:-/tmp/_t1_trace.json}"
FLIGHT="${TIER1_FLIGHT:-/tmp/_t1_flight.json}"
rm -f "$LOG" "$METRICS" "$TRACE" "$FLIGHT"

# QI_METRICS_JSON / QI_TRACE_OUT / QI_FLIGHT_RECORDER (docs/OBSERVABILITY.md,
# ISSUE 6) are exported for EVERY gate below — tests, analyze, chaos soak,
# packed smoke — so the whole tier-1 run lands in one telemetry stream and
# one Perfetto timeline, and any degrade/fault any gate exercises (the
# chaos soak guarantees some) leaves a flight-recorder dump; tier1.yml
# uploads all three as CI artifacts.
export QI_METRICS_JSON="$METRICS" QI_TRACE_OUT="$TRACE" \
    QI_FLIGHT_RECORDER="$FLIGHT"

timeout -k 10 870 env JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist \
    -p no:randomly "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}

echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
if [ -s "$METRICS" ]; then
    echo "TELEMETRY=$METRICS ($(wc -l < "$METRICS") lines)"
fi
if [ -s "$TRACE" ]; then
    echo "TRACE=$TRACE ($(wc -c < "$TRACE") bytes — open in ui.perfetto.dev)"
fi
if [ -s "$FLIGHT" ]; then
    echo "FLIGHT=$FLIGHT (last crash-context dump of the run)"
fi

ANALYZE_OUT="${TIER1_ANALYZE:-/tmp/_t1_analyze.jsonl}"
rm -f "$ANALYZE_OUT"
env JAX_PLATFORMS=cpu python -m tools.analyze --jsonl "$ANALYZE_OUT"
arc=$?
echo "ANALYZE=$ANALYZE_OUT (exit $arc)"

# Chaos-soak smoke: small fixed-seed window, deterministic schedules, no
# ledger writes.  Seed/size overridable for local debugging.
env JAX_PLATFORMS=cpu python tools/soak.py --chaos \
    --instances "${TIER1_CHAOS_INSTANCES:-8}" \
    --seed "${TIER1_CHAOS_SEED:-0}" --no-ledger
crc=$?
echo "CHAOS=exit $crc"

# Packed-sweep smoke (docs/PARITY.md lane-packing invariants): the
# lane-packed vs unpacked bench rows on CPU emulation — exits nonzero on
# any packed/unpacked verdict mismatch; the sweep.pack_* telemetry rides
# the shared (exported) $METRICS stream.
env JAX_PLATFORMS=cpu \
    python benchmarks/sweep_vs_native.py --quick --packed \
    --scc 16 --packed-scc 12 14
prc=$?
echo "PACKED=exit $prc"

# qi-cert gate (docs/OBSERVABILITY.md §Certificates): generate verdict
# certificates for the vendored fixture pairs through the CLI, then
# re-validate every one with the INDEPENDENT stdlib checker — an unsound
# witness or a coverage ledger that does not sum to the window space fails
# the gate.  The CLI's exit code is its verdict (0 true / 1 false); only
# exit > 1 is a crash.
CERTDIR="${TIER1_CERTS:-/tmp/_t1_certs}"
rm -rf "$CERTDIR"
mkdir -p "$CERTDIR"
certrc=0
for fx in trivial_correct trivial_broken nested_correct nested_broken \
          snapshot_correct snapshot_broken; do
    env JAX_PLATFORMS=cpu python -m quorum_intersection_tpu \
        --cert-out "$CERTDIR/$fx.cert.json" \
        < "fixtures/$fx.json" > /dev/null
    vrc=$?
    [ "$vrc" -gt 1 ] && { echo "CERT: solve crashed on $fx (rc=$vrc)"; certrc=1; }
    env JAX_PLATFORMS=cpu python tools/check_cert.py \
        "$CERTDIR/$fx.cert.json" "fixtures/$fx.json" || certrc=1
done
echo "CERTS=$CERTDIR (exit $certrc)"

# qi-prune gate (ISSUE 10): the same six fixture certs with rank-ordered
# windows + block-guard pruning forced through the sweep backend, each
# re-validated by the independent checker — which now re-verifies every
# pruned block with its own stdlib fixpoint evaluator — plus an
# enumeration-ratio assertion on the snapshot pair's correct twin:
# pruning must actually remove windows (ratio < 1.0) while the cert
# stays sound and the verdict stays the manifest's.
PRUNEDIR="${TIER1_PRUNED:-/tmp/_t1_pruned}"
rm -rf "$PRUNEDIR"
mkdir -p "$PRUNEDIR"
prrc=0
for fx in trivial_correct trivial_broken nested_correct nested_broken \
          snapshot_correct snapshot_broken; do
    env JAX_PLATFORMS=cpu QI_SWEEP_ORDER=rank QI_SWEEP_PRUNE=1 \
        python -m quorum_intersection_tpu --backend tpu-sweep \
        --cert-out "$PRUNEDIR/$fx.cert.json" \
        < "fixtures/$fx.json" > /dev/null
    vrc=$?
    [ "$vrc" -gt 1 ] && { echo "PRUNED: solve crashed on $fx (rc=$vrc)"; prrc=1; }
    env JAX_PLATFORMS=cpu python tools/check_cert.py \
        "$PRUNEDIR/$fx.cert.json" "fixtures/$fx.json" || prrc=1
done
env JAX_PLATFORMS=cpu python - "$PRUNEDIR/snapshot_correct.cert.json" <<'PYEOF' || prrc=1
import json, sys
entry = json.load(open(sys.argv[1]))["coverage"]["sccs"][0]
ratio = entry["windows_enumerated"] / entry["window_space"]
assert entry["windows_pruned_guard"] > 0 and ratio < 1.0, entry
print(f"PRUNED: snapshot_correct enumeration ratio {ratio:.4f} "
      f"({entry['windows_pruned_guard']} windows guard-pruned)")
PYEOF
echo "PRUNED_CERTS=$PRUNEDIR (exit $prrc)"

# qi-sparse gate (ISSUE 20): the same six fixture certs with the bitset
# set-intersection twin forced through the sweep backend — the engine is
# an encoding swap, so the UNMODIFIED independent checker must validate
# every cert exactly as it does the dense ones (same coverage ledger
# shape, same witness soundness rules; only provenance.encoding differs).
# Rank ordering + block-guard pruning stay on to cover the composed
# order/prune/bitset path, and a provenance assertion pins that the
# bitset engine actually ran (a silent dense fallback would pass the
# checker and hide the regression).
SPARSEDIR="${TIER1_SPARSE:-/tmp/_t1_sparse}"
rm -rf "$SPARSEDIR"
mkdir -p "$SPARSEDIR"
sprc=0
for fx in trivial_correct trivial_broken nested_correct nested_broken \
          snapshot_correct snapshot_broken; do
    env JAX_PLATFORMS=cpu QI_SWEEP_ENGINE=bitset \
        QI_SWEEP_ORDER=rank QI_SWEEP_PRUNE=1 \
        python -m quorum_intersection_tpu --backend tpu-sweep \
        --cert-out "$SPARSEDIR/$fx.cert.json" \
        < "fixtures/$fx.json" > /dev/null
    vrc=$?
    [ "$vrc" -gt 1 ] && { echo "SPARSE: solve crashed on $fx (rc=$vrc)"; sprc=1; }
    env JAX_PLATFORMS=cpu python tools/check_cert.py \
        "$SPARSEDIR/$fx.cert.json" "fixtures/$fx.json" || sprc=1
done
env JAX_PLATFORMS=cpu python - "$SPARSEDIR" <<'PYEOF' || sprc=1
import glob, json, sys
certs = sorted(glob.glob(sys.argv[1] + "/*.cert.json"))
assert len(certs) == 6, certs
encodings = {json.load(open(p)).get("provenance", {}).get("encoding")
             for p in certs}
assert encodings == {"bitset"}, encodings
print(f"SPARSE: {len(certs)} certs solved by the bitset engine "
      "(provenance.encoding == bitset) and checker-validated")
PYEOF
echo "SPARSE_CERTS=$SPARSEDIR (exit $sprc)"

# Serving-layer smoke (ISSUE 8): open-loop load through a live ServeEngine
# — the driver itself is a parity gate (served verdict == one-shot oracle
# for every request, zero silent drops, exit 1 otherwise).  --churn
# (ISSUE 9) appends the qi-delta churn-parity phase: every request
# advances a churn trace one step, incremental verdicts re-checked
# against the from-scratch oracle per step.  Then the serve chaos soak:
# seeded faults at every serve.* boundary (incl. a forced delta.diff
# mid-churn round on odd seeds) plus one hard-kill mid-stream with
# journal replay, asserting the chaos-gate contract (oracle-equal verdict
# or typed error; zero lost / zero duplicated verdicts across the kill).
# The serve.* / delta.* telemetry rides $METRICS.
env JAX_PLATFORMS=cpu python benchmarks/serve.py --quick --churn
src=$?
echo "SERVE_BENCH=exit $src"
env JAX_PLATFORMS=cpu python tools/soak.py --serve --chaos \
    --instances "${TIER1_SERVE_INSTANCES:-4}" \
    --seed "${TIER1_SERVE_SEED:-0}" --no-ledger
ssrc=$?
echo "SERVE_CHAOS=exit $ssrc"

# qi-fleet smoke (ISSUE 11): the replicated serve tier — an N=2 fleet
# parity gate over the zipfian churn stream (every routed verdict equals
# the one-shot oracle, zero silent drops) including the dedicated
# kill-one-of-N round (the dead worker's unfinished work must re-route to
# its peer with zero lost / zero duplicated verdicts), then the fleet
# chaos soak: seeded fleet.* fault schedules (routing, probing, failover
# replay, shared store) with a kill-one round per even seed.  In-process
# workers (--fleet-local) keep the smoke cheap inside the tier-1 wall
# budget — the routing/failover paths are identical, and the
# subprocess + real-SIGKILL + N=4 scaling coverage runs in the dedicated
# tier1.yml `fleet` job (and the slow-marked test).
env JAX_PLATFORMS=cpu python benchmarks/serve.py --quick --fleet \
    --fleet-n 1,2 --fleet-local
frc=$?
echo "FLEET_BENCH=exit $frc"
env JAX_PLATFORMS=cpu python tools/soak.py --fleet --chaos \
    --instances "${TIER1_FLEET_INSTANCES:-3}" \
    --seed "${TIER1_FLEET_SEED:-0}" --no-ledger
fsrc=$?
echo "FLEET_CHAOS=exit $fsrc"

# qi-query gate (ISSUE 12): the typed-query smoke — the mixed-workload
# parity phase (benchmarks/serve.py --queries: every served
# intersection/relaxed/whatif/analytics verdict equals a direct
# QueryEngine oracle resolution, silent drops exit 1) plus a one-shot
# relaxed CLI round over the adversarial two-family preset with its
# cross-family witness certificate re-validated by the independent
# stdlib checker.
env JAX_PLATFORMS=cpu python benchmarks/serve.py --quick --queries
qrc=$?
echo "QUERY_BENCH=exit $qrc"
env JAX_PLATFORMS=cpu python - <<'PYEOF' || qrc=1
import json, os, subprocess, sys, tempfile
sys.path.insert(0, os.getcwd())
from quorum_intersection_tpu.fbas.synth import two_family_preset
from tools.check_cert import check_certificate

fa, fb = two_family_preset(core=8, watchers=3, broken=True, seed=0)
with tempfile.TemporaryDirectory() as tmp:
    fbp = os.path.join(tmp, "famb.json")
    open(fbp, "w").write(json.dumps(fb))
    certp = os.path.join(tmp, "relaxed.cert.json")
    p = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "query",
         "--kind", "relaxed", "--family-b", fbp, "--cert-out", certp,
         "--backend", "python"],
        input=json.dumps(fa), capture_output=True, text=True)
    assert p.returncode == 1, (p.returncode, p.stderr)  # split found
    notes = check_certificate(json.load(open(certp)), fa)
    print(f"QUERY: relaxed cert re-validated ({notes[-1]})")
PYEOF
echo "QUERY=exit $qrc"

# qi-pulse gate (ISSUE 15, docs/OBSERVABILITY.md §Pulse): cross-process
# trace identity — one request through a 1-subprocess-worker fleet must
# land the SAME trace_id in both the front door's and the worker's
# span lines of one shared telemetry stream (the worker inherits
# QI_METRICS_JSON), with worker spans carrying the front door's request
# span as their wire-stamped remote parent, and the response echoing the
# trace on the wire.
PULSE_METRICS="${TIER1_PULSE_METRICS:-/tmp/_t1_pulse.jsonl}"
rm -f "$PULSE_METRICS"
env JAX_PLATFORMS=cpu QI_METRICS_JSON="$PULSE_METRICS" python - <<'PYEOF'
import json, os, sys
sys.path.insert(0, os.getcwd())
from quorum_intersection_tpu.fbas.synth import majority_fbas
from quorum_intersection_tpu.fleet import FleetEngine
from quorum_intersection_tpu.utils.telemetry import (
    TraceContext, finish, get_run_record,
)

rec = get_run_record()
eng = FleetEngine(1, worker_mode="subprocess", backend="python")
eng.start()
try:
    resp = eng.submit(majority_fbas(3),
                      request_id="pulse-smoke").result(timeout=180.0)
finally:
    eng.stop(drain=True)
assert resp.intersects is True
ctx = TraceContext.from_env(resp.trace)
assert ctx is not None and ctx.trace_id == rec.trace_id, resp.trace
finish()
lines = [json.loads(l) for l in open(os.environ["QI_METRICS_JSON"])]
spans = [l for l in lines
         if l.get("kind") == "span" and l.get("trace_id") == rec.trace_id]
pids = {l["pid"] for l in spans}
assert len(pids) >= 2, f"trace never crossed the pipe (pids {pids})"
grafted = [l for l in spans if l.get("remote_parent_pid") == rec.pid]
assert grafted, "no worker span grafted under the front door's request span"
print(f"PULSE: trace {rec.trace_id} spans from {len(pids)} processes, "
      f"{len(grafted)} grafted under the front door")
PYEOF
purc=$?
echo "PULSE=exit $purc"

# qi-fuse gate (ISSUE 16, README §Serving fusion): the fused vs unfused
# head-to-head on the mixed intersection/what-if stream — the driver
# itself is the gate: cross-request lanes must actually form
# (fuse.cross_request_lanes > 0), the fused tile fill must strictly
# beat the legacy per-request drain, and every fused verdict/cert must
# be byte-identical to its unfused twin (exit 1 otherwise).  The
# window-unset byte-compat, mid-pack cancel partition, and serve.fuse
# degrade contracts are pinned by tests/test_qi_fuse.py in the pytest
# gate above.
env JAX_PLATFORMS=cpu python benchmarks/serve.py --quick --fuse
furc=$?
echo "FUSE_BENCH=exit $furc"

# qi-cost gate (ISSUE 17, docs/OBSERVABILITY.md §Cost & SLO): a mixed
# fused stream where every delivered verdict carries its own bill —
# attributed lane·windows must equal the device total EXACTLY (the
# conservation invariant, 100% attribution), the per-response costs
# must re-sum to the attributed counter, and a live /sloz scrape must
# answer the declared target plus the per-tenant tables.
env JAX_PLATFORMS=cpu QI_SLO="serve_e2e_p99_ms<600000" python - <<'PYEOF'
import json
import urllib.request

from quorum_intersection_tpu.fbas.synth import majority_fbas
from quorum_intersection_tpu.serve import ServeEngine
from quorum_intersection_tpu.utils.metrics_server import MetricsServer
from quorum_intersection_tpu.utils.telemetry import get_run_record

rec = get_run_record()
workload = [majority_fbas(n, prefix=f"T{i}")
            for i, n in enumerate((7, 9, 11, 9, 7, 11))]
engine = ServeEngine(backend="auto", pack=True, fuse_window_ms=200.0)
tickets = [engine.submit(nodes, client=f"ci-{i % 2}")
           for i, nodes in enumerate(workload)]
engine.start()  # queue before start: the drain fuses the whole burst
try:
    responses = [t.result(timeout=300.0) for t in tickets]
finally:
    engine.stop(drain=True, timeout=60.0)
assert all(r.intersects for r in responses)
counters, _ = rec.snapshot()
attr = counters.get("cost.lane_windows_attributed", 0)
total = counters.get("cost.lane_windows_total", 0)
assert total > 0 and attr == total, f"conservation broke: {attr} != {total}"
delivered = sum(r.cost["lane_windows"] for r in responses if r.cost)
assert delivered == attr, f"delivered {delivered} != attributed {attr}"
assert any(r.cost and r.cost.get("fused") for r in responses), \
    "no response billed a fused pack"
srv = MetricsServer(port=0)
try:
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{srv.port}/sloz", timeout=10).read()
finally:
    srv.stop()
payload = json.loads(body)
assert payload["schema"] == "qi-slo/1" and payload["enabled"] is True
tenants = {row["client"] for row in payload["tenants"]["local"]}
assert tenants >= {"ci-0", "ci-1"}, tenants
print(f"COST: {len(responses)} verdicts, {attr} lane-windows attributed "
      f"== device total (100%), /sloz tenants {sorted(tenants)}")
PYEOF
corc=$?
echo "COST=exit $corc"

# Bench-trend sentinel (docs/OBSERVABILITY.md §Trends): the committed
# BENCH_r*.json history rendered as a trend table, informational on
# regressions (the measurement rig varies per round) but hard on schema
# errors — a malformed run wrapper fails the gate.
env JAX_PLATFORMS=cpu python tools/bench_trend.py --informational \
    --telemetry "$METRICS"
trc=$?
echo "TREND=exit $trc"

[ "$rc" -ne 0 ] && exit "$rc"
[ "$arc" -ne 0 ] && exit "$arc"
[ "$crc" -ne 0 ] && exit "$crc"
[ "$prc" -ne 0 ] && exit "$prc"
[ "$certrc" -ne 0 ] && exit "$certrc"
[ "$prrc" -ne 0 ] && exit "$prrc"
[ "$sprc" -ne 0 ] && exit "$sprc"
[ "$src" -ne 0 ] && exit "$src"
[ "$ssrc" -ne 0 ] && exit "$ssrc"
[ "$frc" -ne 0 ] && exit "$frc"
[ "$fsrc" -ne 0 ] && exit "$fsrc"
[ "$qrc" -ne 0 ] && exit "$qrc"
[ "$purc" -ne 0 ] && exit "$purc"
[ "$furc" -ne 0 ] && exit "$furc"
[ "$corc" -ne 0 ] && exit "$corc"
exit "$trc"
