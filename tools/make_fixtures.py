"""Regenerate the vendored fixture corpus under ``fixtures/``.

The reference's four golden JSON fixtures live in the read-only
`/root/reference` checkout and are consumed from there when present; this
corpus makes the repo self-contained (VERDICT r2 §missing-1): structurally
equivalent pass/fail pairs frozen from the deterministic synthetic
generators (`quorum_intersection_tpu/fbas/synth.py`), following the
reference fixtures' de-facto methodology — *same topology, one knob turned*
(SURVEY.md §4.1; e.g. `/root/reference/broken_trivial.json:20` lowers one
threshold 2→1 relative to `correct_trivial.json`).

Every fixture's golden verdict and structural stats are computed here with
the pure-Python oracle and frozen into ``fixtures/MANIFEST.json``; tests and
the bench parity gate replay them against every backend.

Usage::

    python tools/make_fixtures.py        # rewrite fixtures/ deterministically
"""

from __future__ import annotations

import gzip
import json
import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from quorum_intersection_tpu.fbas import synth  # noqa: E402
from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc  # noqa: E402
from quorum_intersection_tpu.fbas.schema import parse_fbas  # noqa: E402
from quorum_intersection_tpu.pipeline import solve  # noqa: E402

FIXTURES = ROOT / "fixtures"


def corpus() -> dict:
    """name → raw stellarbeat-style node list.  Deterministic (seeded)."""
    return {
        # 3-node 2-of-3 pair — the trivial-pair methodology.
        "trivial_correct.json": synth.majority_fbas(3, prefix="TRIV"),
        "trivial_broken.json": synth.majority_fbas(3, broken=True, prefix="TRIV"),
        # Nested inner-set pair (depth 1, the bundled fixtures' max depth).
        "nested_correct.json": synth.hierarchical_fbas(5, 3),
        "nested_broken.json": synth.hierarchical_fbas(5, 3, broken=True),
        # Snapshot-shaped ~150-validator pair: small quorum-bearing core SCC,
        # watcher tail (many singleton SCCs), null qsets, dangling refs —
        # the structural statistics of /root/reference/correct.json scaled up.
        "snapshot_correct.json": synth.stellar_like_fbas(),
        "snapshot_broken.json": synth.stellar_like_fbas(broken=True),
        # Dump-scale (~3k nodes): frontend/encode/PageRank scale fixture
        # (gzipped — see write step).  Core SCC stays 21 nodes so the verdict
        # is cheap; the frontier is the O(n) / O(U²) machinery around it.
        "dump_scale_correct.json.gz": synth.stellar_like_fbas(
            n_watchers=2800, n_null=150, n_dangling=40, seed=7
        ),
    }


def stats_for(nodes: list) -> dict:
    graph = build_graph(parse_fbas(nodes), dangling="strict")
    count, comp = tarjan_scc(graph.n, graph.succ)
    sccs = group_sccs(graph.n, comp, count)
    return {
        "nodes": graph.n,
        "n_sccs": count,
        "largest_scc": max(len(s) for s in sccs),
        "null_qsets": sum(1 for q in graph.qsets if q.threshold is None),
        "dangling_refs": graph.dangling_refs,
    }


def main() -> int:
    FIXTURES.mkdir(exist_ok=True)
    manifest = {}
    for name, nodes in corpus().items():
        payload = json.dumps(nodes, indent=1 if "dump" not in name else None)
        path = FIXTURES / name
        if name.endswith(".gz"):
            # mtime=0 keeps the gzip byte-identical across regenerations.
            path.write_bytes(
                gzip.compress(payload.encode(), compresslevel=9, mtime=0)
            )
        else:
            path.write_text(payload + "\n")
        res = solve(nodes, backend="python")
        manifest[name] = {
            "verdict": res.intersects,
            **stats_for(nodes),
        }
        print(f"{name}: verdict={res.intersects} {manifest[name]}")
    (FIXTURES / "MANIFEST.json").write_text(json.dumps(manifest, indent=1) + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
