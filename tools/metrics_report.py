#!/usr/bin/env python
"""Render a ``qi-telemetry/1`` JSONL stream into per-phase / per-window
tables (ISSUE 2 tentpole — the read side of utils/telemetry.py).

The stream may span multiple processes (the bench driver's phase children,
CLI subprocesses under the test suite): spans and counters aggregate across
all of them, with the process count reported up front.  Malformed lines are
counted and skipped, never fatal — a SIGKILLed run leaves a ragged tail.

Usage::

    python tools/metrics_report.py metrics.jsonl            # full report
    python tools/metrics_report.py metrics.jsonl --windows 8  # + window tail
    python tools/metrics_report.py a.jsonl --diff b.jsonl   # delta table

Since ISSUE 6 the per-phase section renders spans as a TREE (indent by
parent_id, scoped per pid so multi-process id collisions never graft one
process's spans onto another's), and ``--diff`` compares two streams'
counters/gauges/span totals — the delta engine ``tools/bench_trend.py``
reuses for its telemetry half.

Since ISSUE 15 (qi-pulse) the tree GRAFTS across processes on
wire-carried trace context: a span whose line carries
``remote_parent_span``/``remote_parent_pid`` (a serve worker's span
adopted under a fleet front door's request span) hangs under that remote
parent instead of rooting its own tree.  The pid scoping is unchanged for
spans without those fields, so a pre-pulse single-process stream renders
byte-identically (pinned by tests/test_qi_pulse.py).  ``kind:
"histogram"`` lines (the mergeable pulse latency histograms) aggregate
bucket-wise across processes and render as their own section, and
``--chrome OUT`` exports the stream as Chrome/Perfetto trace-event JSON —
with ``--merge``, cross-process parent links additionally render as flow
arrows, so one fleet request reads as one flow in the timeline.
"""

from __future__ import annotations

import argparse
import json
import sys
from collections import defaultdict
from typing import Dict, List


def _merge_histogram(into: Dict[str, dict], line: dict) -> None:
    """Fold one ``kind: histogram`` line into the per-name aggregate —
    bucket-wise addition, the primitive's own merge law, so a
    multi-process stream's histograms read as one fleet distribution.
    Mismatched bucket ladders keep the first and count the line bad."""
    name = str(line.get("name", "?"))
    cur = into.get(name)
    if cur is None:
        into[name] = {
            "bounds": list(line.get("bounds") or ()),
            "counts": [int(c) for c in line.get("counts") or ()],
            "count": int(line.get("count") or 0),
            "sum": float(line.get("sum") or 0.0),
        }
        return
    if list(line.get("bounds") or ()) != cur["bounds"]:
        raise ValueError("histogram bounds mismatch")
    counts = [int(c) for c in line.get("counts") or ()]
    if len(counts) != len(cur["counts"]):
        raise ValueError("histogram counts length mismatch")
    cur["counts"] = [a + b for a, b in zip(cur["counts"], counts)]
    cur["count"] += int(line.get("count") or 0)
    cur["sum"] += float(line.get("sum") or 0.0)


def load_stream(path: str) -> dict:
    """Parse one JSONL file into {spans, events, counters, gauges,
    histograms, meta}."""
    spans: List[dict] = []
    events: List[dict] = []
    counters: Dict[str, float] = defaultdict(float)
    gauges: Dict[str, object] = {}
    histograms: Dict[str, dict] = {}
    tenants: List[dict] = []
    meta: List[dict] = []
    bad = 0
    with open(path, "r", encoding="utf-8") as fh:
        for raw in fh:
            raw = raw.strip()
            if not raw:
                continue
            try:
                line = json.loads(raw)
                kind = line["kind"]
            except (json.JSONDecodeError, TypeError, KeyError):
                bad += 1
                continue
            if kind == "span":
                spans.append(line)
            elif kind == "event":
                events.append(line)
            elif kind == "counter":
                counters[line.get("name", "?")] += line.get("value", 0) or 0
            elif kind == "gauge":
                gauges[line.get("name", "?")] = line.get("value")
            elif kind == "histogram":
                try:
                    _merge_histogram(histograms, line)
                except ValueError:
                    bad += 1
            elif kind == "tenants":
                # qi-cost (ISSUE 17): one per-tenant cost table per process,
                # emitted at record finish; merged client-wise on render.
                tenants.append(line)
            elif kind == "meta":
                meta.append(line)
            # "log" lines (QI_LOG_JSON interleaving) pass through silently
    return {
        "spans": spans,
        "events": events,
        "counters": dict(counters),
        "gauges": gauges,
        "histograms": histograms,
        "tenants": tenants,
        "meta": meta,
        "bad_lines": bad,
    }


def _table(rows: List[List[str]], header: List[str]) -> str:
    widths = [
        max(len(str(r[i])) for r in [header] + rows) for i in range(len(header))
    ]
    def fmt(row):
        return "  ".join(str(c).ljust(w) for c, w in zip(row, widths)).rstrip()
    lines = [fmt(header), fmt(["-" * w for w in widths])]
    lines += [fmt(r) for r in rows]
    return "\n".join(lines)


def _parent_key(sp: dict) -> tuple:
    """The parent lookup key of one span: its in-process ``parent_id``
    scoped by pid or — for a thread-root span carrying wire-adopted trace
    context (qi-pulse, ISSUE 15) — the REMOTE parent ``(pid, span_id)``
    the fleet front door stamped on dispatch.  Pre-pulse spans have
    neither field set beyond parent_id, so old streams resolve exactly
    as they always did (pid-scoped, cross-process joins impossible)."""
    if sp.get("parent_id") is not None:
        return (sp.get("pid", 0), sp.get("parent_id"))
    if sp.get("remote_parent_span") is not None:
        return (sp.get("remote_parent_pid", 0), sp.get("remote_parent_span"))
    return (None, None)


def _span_paths(spans: List[dict]) -> List[tuple]:
    """Name-path of every span, root-first (ISSUE 6 satellite).

    Parent links are ``(pid, parent_id)`` — span ids are per-process
    counters, so a multi-process stream must scope the lookup by pid (old
    streams without a ``pid`` field fall back to one shared scope).  A
    parent beyond the retention cap roots the subtree rather than
    dropping it.  Cross-PROCESS joins happen only on wire-carried trace
    context: a span with ``remote_parent_span``/``remote_parent_pid``
    grafts under that remote span (qi-pulse, ISSUE 15) — never on a bare
    id collision.
    """
    by_key = {
        (sp.get("pid", 0), sp.get("span_id")): sp
        for sp in spans
        if sp.get("span_id") is not None
    }
    paths = []
    for sp in spans:
        chain = [sp.get("name", "?")]
        cur = sp
        seen = set()
        while True:
            key = _parent_key(cur)
            if key == (None, None) or key in seen:
                break  # root, or defensive: a cyclic id would otherwise spin
            seen.add(key)
            cur = by_key.get(key)
            if cur is None:
                break
            chain.append(cur.get("name", "?"))
        paths.append((tuple(reversed(chain)), sp))
    return paths


def span_table(spans: List[dict]) -> str:
    """Span TREE: aggregate by root-to-leaf name path, indent by depth —
    a race's arms and a ladder's rungs read as the hierarchy they are,
    not an alphabet of flat rows."""
    agg: Dict[tuple, List[float]] = {}
    for path, sp in _span_paths(spans):
        sec = sp.get("seconds")
        if sec is None:
            continue
        cur = agg.setdefault(path, [0, 0.0, 0.0])
        cur[0] += 1
        cur[1] += sec
        cur[2] = max(cur[2], sec)
    # Depth-first render order: a path sorts directly under its prefix;
    # sibling subtrees order by total seconds descending.
    totals = {p: t for p, (c, t, mx) in agg.items()}

    def sort_key(path: tuple):
        # Each ancestor segment contributes (-subtree_total, name) so heavy
        # subtrees come first but children stay under their parent.
        key = []
        for d in range(len(path)):
            prefix = path[: d + 1]
            subtotal = sum(t for p, t in totals.items() if p[: d + 1] == prefix)
            key.append((-subtotal, path[d]))
        return key

    rows = [
        ["  " * (len(path) - 1) + path[-1], int(c), f"{t:.3f}",
         f"{t / c * 1000:.2f}", f"{mx * 1000:.2f}"]
        for path, (c, t, mx) in sorted(agg.items(), key=lambda kv: sort_key(kv[0]))
    ]
    if not rows:
        return "(no spans)"
    return _table(rows, ["span", "count", "total_s", "mean_ms", "max_ms"])


def window_tables(events: List[dict], tail: int) -> str:
    windows = [e for e in events if e.get("name") == "sweep.window"]
    if not windows:
        return "(no sweep windows)"
    buckets: Dict[object, List[float]] = {}
    total_cand = 0
    total_sec = 0.0
    for w in windows:
        attrs = w.get("attrs", {})
        cand = attrs.get("candidates", 0) or 0
        sec = attrs.get("seconds", 0.0) or 0.0
        total_cand += cand
        total_sec += sec
        cur = buckets.setdefault(attrs.get("steps_per_call", "?"), [0, 0, 0.0])
        cur[0] += 1
        cur[1] += cand
        cur[2] += sec
    rows = [
        [str(spc), int(n), int(cand), f"{sec:.3f}",
         f"{cand / sec:,.0f}" if sec > 0 else "-"]
        for spc, (n, cand, sec) in sorted(
            buckets.items(), key=lambda kv: str(kv[0])
        )
    ]
    out = [
        f"windows: {len(windows)}   candidates: {total_cand:,}   "
        + (f"drain rate: {total_cand / total_sec:,.0f} cand/s"
           if total_sec > 0 else "drain rate: -"),
        _table(rows, ["steps_per_call", "windows", "candidates", "seconds",
                      "rate_cand_s"]),
    ]
    if tail > 0:
        out.append("")
        out.append(f"last {min(tail, len(windows))} windows:")
        rows = [
            [f"{w.get('t_s', 0):.3f}",
             str(w["attrs"].get("start", "?")),
             str(w["attrs"].get("candidates", "?")),
             str(w["attrs"].get("steps_per_call", "?")),
             str(w["attrs"].get("rate", "?"))]
            for w in windows[-tail:]
        ]
        out.append(_table(rows, ["t_s", "start", "candidates",
                                 "steps_per_call", "rate"]))
    return "\n".join(out)


def event_summary(events: List[dict]) -> str:
    lines = []
    by_name: Dict[str, int] = defaultdict(int)
    for e in events:
        by_name[e.get("name", "?")] += 1
    if by_name:
        lines.append(_table(
            [[n, c] for n, c in sorted(by_name.items(), key=lambda kv: -kv[1])],
            ["event", "count"],
        ))
    races = [e for e in events if e.get("name") == "race"]
    for r in races:
        a = r.get("attrs", {})
        lines.append(
            f"race @ {r.get('t_s', 0):.3f}s: winner={a.get('winner')} "
            f"oracle={a.get('oracle_outcome')} "
            f"oracle_s={a.get('oracle_seconds')} "
            f"sweep_s={a.get('sweep_seconds', '-')} "
            f"loser_joined={a.get('loser_joined')} "
            f"join_s={a.get('loser_join_seconds', '-')}"
        )
    for e in events:
        if e.get("name") == "route.decision":
            a = e.get("attrs", {})
            lines.append(
                f"route @ {e.get('t_s', 0):.3f}s: |scc|={a.get('scc')} -> "
                f"{a.get('engine')} ({a.get('reason')})"
            )
    # Static-analysis findings ride the same stream (ISSUE 3: the analyze
    # job's artifact is qi-telemetry/1 too, so one renderer serves both).
    for e in events:
        if e.get("name") == "analyze.finding":
            a = e.get("attrs", {})
            lines.append(
                f"finding [{a.get('pass')}/{a.get('rule')}] "
                f"{a.get('file')}:{a.get('line')}: {a.get('message')}"
            )
    return "\n".join(lines) if lines else "(no events)"


def _wire_quantile(hist: dict, pct: float) -> float:
    """Bucket-resolution quantile of one aggregated histogram (nearest
    rank; the upper edge of the holding bucket) — stdlib-only twin of
    ``Histogram.quantile_ms`` so this reporter stays import-free of the
    package (the bench-trend CI job's contract)."""
    total = int(hist.get("count") or 0)
    bounds = hist.get("bounds") or []
    if total <= 0 or not bounds:
        return 0.0
    rank = max(-(-pct * total // 100), 1)  # ceil without math
    seen = 0
    for ix, n in enumerate(hist.get("counts") or []):
        seen += int(n)
        if seen >= rank:
            return float(bounds[min(ix, len(bounds) - 1)])
    return float(bounds[-1])


def histogram_table(histograms: Dict[str, dict]) -> str:
    """The qi-pulse latency-distribution section: per histogram the exact
    count/sum plus bucket-resolution p50/p99 estimates — aggregated
    bucket-wise across every process in the stream."""
    rows = [
        [name, int(h.get("count") or 0), f"{float(h.get('sum') or 0.0):.3f}",
         f"{(float(h.get('sum') or 0.0) / h['count']):.3f}" if h.get("count") else "-",
         f"{_wire_quantile(h, 50.0):g}", f"{_wire_quantile(h, 99.0):g}"]
        for name, h in sorted(histograms.items())
    ]
    if not rows:
        return "(no histograms)"
    return _table(rows, ["histogram", "count", "sum_ms", "mean_ms",
                         "p50_le_ms", "p99_le_ms"])


def merge_tenants(tenant_lines: List[dict]) -> Dict[str, dict]:
    """Fold the per-process ``kind: tenants`` lines (qi-cost, ISSUE 17)
    into one client→cost view — field-wise addition, the table's own merge
    law, the stdlib twin of ``cost.merge_tenant_snapshots`` so this
    reporter stays import-free of the package."""
    merged: Dict[str, dict] = {}
    for line in tenant_lines:
        table = line.get("tenants")
        if not isinstance(table, dict):
            continue
        for client, row in table.items():
            if not isinstance(row, dict):
                continue
            cur = merged.setdefault(str(client), {
                "requests": 0, "lane_windows": 0, "macs": 0,
                "credit_lane_windows": 0, "device_s": 0.0,
            })
            for key in ("requests", "lane_windows", "macs",
                        "credit_lane_windows"):
                cur[key] += int(row.get(key) or 0)
            cur["device_s"] += float(row.get("device_s") or 0.0)
    return merged


def tenant_table_section(tenant_lines: List[dict], top: int) -> str:
    """The ``--top N`` per-tenant device-cost table: who occupied the MXU,
    ranked by attributed lane·windows (ties by request count)."""
    merged = merge_tenants(tenant_lines)
    ranked = sorted(
        merged.items(),
        key=lambda kv: (-kv[1]["lane_windows"], -kv[1]["requests"], kv[0]),
    )
    rows = [
        [client, int(r["requests"]), int(r["lane_windows"]),
         int(r["credit_lane_windows"]), int(r["macs"]),
         f"{r['device_s']:.6f}"]
        for client, r in ranked[:max(top, 0) or len(ranked)]
    ]
    if not rows:
        return "(no tenant costs)"
    head = (f"tenants: {len(merged)}"
            + (f"   (top {top} by lane_windows)"
               if 0 < top < len(merged) else ""))
    return head + "\n" + _table(
        rows, ["client", "requests", "lane_windows", "credit_lw", "macs",
               "device_s"],
    )


def export_chrome(data: dict, out_path: str, merge: bool = False) -> int:
    """Export a loaded stream as Chrome/Perfetto trace-event JSON
    (ISSUE 15): spans become complete duration events on their real
    pid/tid tracks (wall-clock anchored per process by the meta lines),
    telemetry events become instant marks.  With ``merge``, every span
    carrying wire-adopted remote-parent context additionally emits a
    flow-event pair from the front door's request span to the worker's
    span — one fleet request renders as ONE flow arrow across process
    tracks.  Returns the number of trace events written."""
    anchors = {
        m.get("pid", 0): float(m.get("t_wall") or 0.0) for m in data["meta"]
    }
    fallback = min((t for t in anchors.values() if t), default=0.0)

    def ts(pid: object, rel: object) -> float:
        anchor = anchors.get(pid) or fallback
        return round((anchor + float(rel or 0.0)) * 1e6, 1)

    out: List[dict] = []
    for m in data["meta"]:
        out.append({
            "ph": "M", "name": "process_name", "pid": m.get("pid", 0),
            "tid": 0,
            "args": {"name": (
                f"{m.get('argv0') or 'python'} (pid {m.get('pid')}, "
                f"trace {m.get('trace_id', '?')})"
            )},
        })
    for sp in data["spans"]:
        if sp.get("seconds") is None:
            continue
        out.append({
            "ph": "X", "cat": "span", "name": sp.get("name", "?"),
            "pid": sp.get("pid", 0), "tid": int(sp.get("tid") or 0),
            "ts": ts(sp.get("pid", 0), sp.get("start_s")),
            "dur": max(round(float(sp["seconds"]) * 1e6, 1), 1.0),
            "args": sp.get("attrs") or {},
        })
    for ev in data["events"]:
        out.append({
            "ph": "i", "cat": "event", "name": ev.get("name", "?"),
            "pid": ev.get("pid", 0), "tid": int(ev.get("tid") or 0),
            "ts": ts(ev.get("pid", 0), ev.get("t_s")), "s": "t",
            "args": ev.get("attrs") or {},
        })
    if merge:
        by_key = {
            (sp.get("pid", 0), sp.get("span_id")): sp
            for sp in data["spans"] if sp.get("span_id") is not None
        }
        flow = 0
        for sp in data["spans"]:
            remote = sp.get("remote_parent_span")
            if remote is None:
                continue
            parent = by_key.get((sp.get("remote_parent_pid", 0), remote))
            if parent is None:
                continue  # the front-door half was not in this stream
            flow += 1
            out.append({
                "ph": "s", "cat": "qi-pulse", "name": "request", "id": flow,
                "pid": parent.get("pid", 0),
                "tid": int(parent.get("tid") or 0),
                "ts": ts(parent.get("pid", 0), parent.get("start_s")),
            })
            out.append({
                "ph": "f", "bp": "e", "cat": "qi-pulse", "name": "request",
                "id": flow,
                "pid": sp.get("pid", 0), "tid": int(sp.get("tid") or 0),
                "ts": ts(sp.get("pid", 0), sp.get("start_s")),
            })
    with open(out_path, "w", encoding="utf-8") as fh:
        json.dump(out, fh)
    return len(out)


def scalar_table(counters: Dict[str, float], gauges: Dict[str, object]) -> str:
    def pretty(v):
        if isinstance(v, float) and v.is_integer():
            return int(v)
        return v

    rows = [
        [name, "counter", pretty(value)]
        for name, value in sorted(counters.items())
    ]
    rows += [
        [name, "gauge", pretty(value)] for name, value in sorted(gauges.items())
    ]
    if not rows:
        return "(no counters/gauges)"
    return _table(rows, ["name", "kind", "value"])


def diff_streams(a: dict, b: dict) -> List[List[str]]:
    """Rows comparing two loaded streams (ISSUE 6 satellite): counters,
    numeric gauges, and per-name span totals, with absolute and percentage
    deltas (b relative to a).  Reused by ``tools/bench_trend.py`` for its
    telemetry half — one delta implementation, one formatting."""
    def span_totals(data: dict) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for sp in data["spans"]:
            sec = sp.get("seconds")
            if sec is not None:
                out[f"span:{sp.get('name', '?')}"] = (
                    out.get(f"span:{sp.get('name', '?')}", 0.0) + sec
                )
        return out

    def numeric(d: Dict[str, object]) -> Dict[str, float]:
        return {
            k: float(v) for k, v in d.items()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        }

    rows: List[List[str]] = []
    for kind, da, db in (
        ("counter", a["counters"], b["counters"]),
        ("gauge", numeric(a["gauges"]), numeric(b["gauges"])),
        ("span_s", span_totals(a), span_totals(b)),
    ):
        for name in sorted(set(da) | set(db)):
            va, vb = da.get(name), db.get(name)
            if va is None or vb is None:
                delta = pct = "-"
            else:
                delta = f"{vb - va:+.6g}"
                pct = f"{(vb - va) / va * 100:+.1f}%" if va else "-"
            rows.append([
                name, kind,
                "-" if va is None else f"{va:.6g}",
                "-" if vb is None else f"{vb:.6g}",
                delta, pct,
            ])
    return rows


def render_diff(path_a: str, path_b: str) -> str:
    rows = diff_streams(load_stream(path_a), load_stream(path_b))
    head = f"qi-telemetry diff: {path_a} -> {path_b}"
    if not rows:
        return head + "\n(nothing to compare)"
    return head + "\n" + _table(
        rows, ["name", "kind", "a", "b", "delta", "delta_pct"]
    )


def render(path: str, tail: int = 0, top: int = 10) -> str:
    data = load_stream(path)
    pids = {m.get("pid") for m in data["meta"]}
    head = (
        f"qi-telemetry report: {path}\n"
        f"processes: {len(pids) or 1}   spans: {len(data['spans'])}   "
        f"events: {len(data['events'])}"
        + (f"   malformed lines skipped: {data['bad_lines']}"
           if data["bad_lines"] else "")
    )
    sections = [
        head,
        "\n== per-phase spans ==\n" + span_table(data["spans"]),
        "\n== sweep windows ==\n" + window_tables(data["events"], tail),
        "\n== events ==\n" + event_summary(data["events"]),
        "\n== counters / gauges ==\n"
        + scalar_table(data["counters"], data["gauges"]),
    ]
    if data["histograms"]:
        # Appended only when the stream carries histogram lines, so a
        # pre-pulse stream's report stays byte-identical (the qi-pulse
        # regression contract).
        sections.append(
            "\n== latency histograms (qi-pulse) ==\n"
            + histogram_table(data["histograms"])
        )
    if data["tenants"]:
        # Same conditional-append discipline: a stream without cost lines
        # renders byte-identically to its pre-cost report.
        sections.append(
            "\n== per-tenant device cost (qi-cost) ==\n"
            + tenant_table_section(data["tenants"], top)
        )
    return "\n".join(sections)


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("path", help="qi-telemetry/1 JSONL file")
    parser.add_argument("--windows", type=int, default=0, metavar="N",
                        help="also list the last N sweep windows")
    parser.add_argument("--top", type=int, default=10, metavar="N",
                        help="per-tenant cost table depth (qi-cost): show "
                             "the N costliest clients by attributed "
                             "lane-windows (0 = all; the section renders "
                             "only when the stream carries cost lines)")
    parser.add_argument("--diff", metavar="PATH_B", default=None,
                        help="compare PATH (baseline) against PATH_B: "
                             "counter/gauge/span-total deltas instead of "
                             "the full report (bench_trend reuses this)")
    parser.add_argument("--chrome", metavar="OUT", default=None,
                        help="also export the stream as Chrome/Perfetto "
                             "trace-event JSON (open in ui.perfetto.dev)")
    parser.add_argument("--merge", action="store_true",
                        help="with --chrome: render wire-carried "
                             "cross-process parent links as flow arrows — "
                             "one fleet request reads as one flow")
    args = parser.parse_args()
    if args.merge and not args.chrome:
        print("--merge requires --chrome OUT", file=sys.stderr)
        return 1
    try:
        if args.diff:
            print(render_diff(args.path, args.diff))
        else:
            print(render(args.path, args.windows, args.top))
        if args.chrome:
            n = export_chrome(load_stream(args.path), args.chrome,
                              merge=args.merge)
            print(f"chrome trace: {args.chrome} ({n} events"
                  + (", merged flows" if args.merge else "") + ")")
    except OSError as exc:
        print(f"cannot read {args.path}: {exc}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
