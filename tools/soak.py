"""Reproducible differential soak harness (VERDICT r3 §next-4).

One command re-runs — and EXTENDS — the cross-engine soak behind the
"zero mismatches" claims (docs/ROUND3_NOTES.md): seed-controlled synthetic
instances from every generator family (random / hierarchical / majority /
stellar-like / benchmark), each solved by every engine that applies —

- host oracles: ``python`` (reference semantics re-model) and ``cpp``
  (native CSR oracle) — always;
- device engine: ``tpu-frontier`` — always (the round-trip hybrid was
  retired in r5; ledger windows before that include it);
- ``tpu-sweep`` — when the largest SCC fits an exhaustive 2^(|scc|-1)
  enumeration cheaply (≤ SWEEP_SCC_LIMIT).

and cross-checked on:

- **verdicts** (all engines must agree);
- **witnesses** (every ``false`` verdict's (q1, q2) must be two disjoint
  REAL quorums under the host set semantics — engines may legitimately
  return *different* valid pairs);
- **minimal-quorum counts** (enumeration completeness): cpp vs python
  always (stats lockstep); frontier vs python unless the oracle's cpp:221
  bestNode fallback fired (``best_node_fallback`` stat — PARITY.md D15:
  the one branch where the enumerations legitimately diverge).

Results append to a persistent ledger
(``benchmarks/results/soak_ledger.json``) so the instance total grows
round over round instead of resetting; re-running an already-recorded
``(seed, instances)`` window is detected and skipped unless ``--force``.

**Chaos mode** (ISSUE 4): ``--chaos`` solves each instance under a SEEDED
fault schedule (``utils/faults.py sample_plan``) on three auto-router
configurations — the sequential chain, the racing chain, and a forced
sweep-rung chain (so device faults actually fire on instances the host
oracle would otherwise answer in microseconds) — and asserts the hardened
pipeline's contract: the verdict equals the fault-free sequential chain,
or the run fails LOUDLY with a typed error (``FaultInjected`` family /
``RungFailed``).  A silent verdict flip or an untyped crash is a mismatch,
exit 1.  Same ``--seed`` ⇒ same schedules ⇒ same firing sequence, so a
chaos failure reproduces exactly.

**Serve mode** (ISSUE 8): ``--serve`` soaks the long-lived serving layer
(``quorum_intersection_tpu/serve.py``) instead of one-shot solves.  Two
rounds per seed:

1. **In-process chaos** (with ``--chaos``): a churn-trace request stream is
   driven through a live ``ServeEngine`` under a seeded serving-layer
   fault schedule (``utils/faults.py sample_serve_plan`` — every
   ``serve.*`` boundary is drawable) and the chaos contract is asserted
   per request: the served verdict equals the fault-free ``python``-oracle
   verdict for its snapshot, or the request fails LOUDLY with a typed
   error (``ServeError`` family / ``FaultInjected``) — a silent drop (a
   ticket that never resolves) or a flipped verdict is a mismatch.  A
   fault-free restart on the same journal then re-replays: replayed
   verdicts must also match the oracle.
2. **Kill-and-replay**: a real ``python -m quorum_intersection_tpu serve``
   subprocess with a request journal is fed the stream, hard-killed
   (``SIGKILL``) mid-drain (a ``serve.drain=hang`` rule holds the drain so
   work is genuinely in flight), and restarted with ``--replay-only``.
   The journal accounting must balance exactly: every journaled request
   reaches exactly one outcome across the kill (answered before it, or
   replayed after it) — zero lost, zero duplicated — and every verdict on
   both sides of the kill equals the oracle's.
3. **Forced qi-delta degradation** (ISSUE 9, odd ``--chaos`` seeds): the
   same stream re-runs under an explicit ``delta.diff=error@2+`` plan, so
   the incremental differ fails *mid-churn* — the first drain batch runs
   incrementally, every later one must degrade to the full re-solve chain
   with verdicts still oracle-identical, and the round fails if the forced
   plan never fired (the differ path silently bypassed).

**Fleet mode** (ISSUE 11): ``--fleet`` soaks the replicated serve tier
(``quorum_intersection_tpu/fleet.py``): each seed drives a churn-trace
stream through a live 2-worker fleet — with ``--chaos``, under a seeded
fleet-tier fault schedule (``utils/faults.py sample_fleet_plan``: routing,
probing, failover replay and the shared store tier are all drawable) —
and even seeds additionally hard-kill one worker mid-stream so the ring
eviction + journal failover path runs under the same contract: every
request reaches exactly one outcome, the oracle verdict or a typed error,
with zero lost and zero duplicated verdicts across the kill.

**Socket-mesh round** (ISSUE 19, ``--fleet --chaos``): each seed
additionally joins a REAL ``serve --socket`` subprocess over TCP
(``fleet --join`` worker mode) under a seeded wire-tier schedule
(``utils/faults.py sample_mesh_plan``: join, lease and journal-ship are
drawable), and even seeds SIGSTOP the peer mid-stream — a PARTITION, not
a death: the peer is suspected and its requests hedge to the next arc
owner — then SIGCONT it so the rejoin path heals the mesh.  The contract
is unchanged: every admitted request reaches exactly one outcome, the
oracle verdict or a typed error, across partition, hedge and rejoin.

Usage::

    python tools/soak.py                      # 40 instances from seed 0
    python tools/soak.py --instances 100 --seed 1000
    python tools/soak.py --no-ledger          # dry run, don't record
    python tools/soak.py --chaos --instances 20 --seed 0
    python tools/soak.py --serve --chaos --instances 6 --seed 0
    python tools/soak.py --fleet --chaos --instances 4 --seed 0
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import random
import signal
import subprocess
import sys
import tempfile
import time

_REPO = pathlib.Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:  # runnable from any cwd without installation
    sys.path.insert(0, str(_REPO))

LEDGER = _REPO / "benchmarks" / "results" / "soak_ledger.json"
SWEEP_SCC_LIMIT = 15


def make_instance(seed: int, profile: str = "small"):
    """Seed → (kind, description, node list).  The mix mirrors the
    generator families the differential suite covers, with ~40% broken
    twins so the witness path is exercised as hard as the safe path.
    ``profile="large"`` scales every family up to routing-relevant SCC
    sizes (14-20) — slower per instance, but it soaks the arena-spill and
    batched-flag paths the small profile rarely reaches."""
    from quorum_intersection_tpu.fbas import synth

    big = profile == "large"
    rng = random.Random(seed)
    kind = rng.choice(["random", "hierarchical", "majority", "stellar", "benchmark"])
    broken = rng.random() < 0.4
    if kind == "random":
        n = rng.randint(14, 20) if big else rng.randint(6, 16)
        data = synth.random_fbas(
            n, seed=seed, nested_prob=rng.random() * 0.5,
            null_prob=rng.random() * 0.2, dangling_prob=rng.random() * 0.2,
        )
        desc = f"random(n={n})"
    elif kind == "hierarchical":
        # Large profile keeps the SCC in the claimed 14-20+ band: 5x3=15 up
        # to 6x4=24 (orgs alone with per 2-3 could dip to 10 nodes).
        orgs = rng.randint(5, 6) if big else rng.randint(3, 4)
        per = rng.randint(3, 4) if big else rng.randint(2, 3)
        data = synth.hierarchical_fbas(orgs, per, broken=broken)
        desc = f"hier({orgs}x{per},broken={broken})"
    elif kind == "majority":
        n = rng.randint(14, 18) if big else rng.randint(5, 13)
        data = synth.majority_fbas(n, broken=broken)
        desc = f"majority(n={n},broken={broken})"
    elif kind == "stellar":
        orgs = rng.randint(5, 6) if big else rng.randint(3, 4)
        data = synth.stellar_like_fbas(
            n_core_orgs=orgs, per_org=3, n_watchers=rng.randint(8, 25),
            n_null=rng.randint(0, 6), n_dangling=rng.randint(0, 3),
            broken=broken, seed=seed,
        )
        desc = f"stellar(orgs={orgs},broken={broken})"
    else:
        core = rng.randint(13, 16) if big else rng.randint(7, 10)
        n_total = core + rng.randint(8, 20)
        data = synth.benchmark_fbas(
            n_total, core, nested_watchers=rng.random() < 0.5,
            broken=broken, seed=seed,
        )
        desc = f"benchmark(n={n_total},core={core},broken={broken})"
    return kind, desc, data


def witness_valid(graph, res) -> bool:
    """A false verdict must ship two disjoint real quorums (host set
    semantics) — except the no-quorum-anywhere guard case, which has none."""
    from quorum_intersection_tpu.fbas.semantics import is_quorum

    if res.q1 is None and res.q2 is None:
        return res.stats.get("reason") == "scc_guard" and not res.quorum_scc_ids
    return (
        res.q1 is not None and res.q2 is not None
        and not set(res.q1) & set(res.q2)
        and is_quorum(graph, res.q1) and is_quorum(graph, res.q2)
    )


def run_instance(seed: int, profile: str = "small") -> dict:
    """Solve one instance on every applicable engine; return the record
    with any mismatches listed (empty list = clean)."""
    from quorum_intersection_tpu.backends.cpp import CppOracleBackend
    from quorum_intersection_tpu.backends.tpu.frontier import TpuFrontierBackend
    from quorum_intersection_tpu.backends.tpu.sweep import TpuSweepBackend
    from quorum_intersection_tpu.fbas.graph import build_graph, group_sccs, tarjan_scc
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.pipeline import solve

    kind, desc, data = make_instance(seed, profile)
    graph = build_graph(parse_fbas(data))
    count, comp = tarjan_scc(graph.n, graph.succ)
    max_scc = max(len(s) for s in group_sccs(graph.n, comp, count))

    engines = {
        "python": "python",
        "cpp": CppOracleBackend(),
        # Alternate the flagged-state pipeline so BOTH paths soak: "device"
        # (batched leave-one-out + probe fixpoints) on even seeds, the
        # serial exact host path on odd ones.
        "frontier": TpuFrontierBackend(
            arena=2048, pop=128,
            flag_check="device" if seed % 2 == 0 else "host",
        ),
    }
    if max_scc <= SWEEP_SCC_LIMIT:
        engines["sweep"] = TpuSweepBackend()

    results, mismatches = {}, []
    for name, backend in engines.items():
        try:
            results[name] = solve(data, backend=backend)
        except Exception as exc:  # noqa: BLE001 — an engine crash IS a finding
            mismatches.append(f"{name} crashed: {type(exc).__name__}: {exc}")
    if "python" not in results:
        return {"seed": seed, "kind": kind, "desc": desc,
                "engines": list(results), "mismatches": mismatches}

    oracle = results["python"]
    for name, res in results.items():
        if res.intersects is not oracle.intersects:
            mismatches.append(
                f"{name} verdict {res.intersects} != python {oracle.intersects}"
            )
        if not res.intersects and not witness_valid(graph, res):
            mismatches.append(f"{name} witness invalid: q1={res.q1} q2={res.q2}")

    # Enumeration-completeness count parity on safe single-SCC searches.
    if oracle.intersects and oracle.stats.get("reason") != "scc_guard":
        want = oracle.stats.get("minimal_quorums")
        if "cpp" in results:
            got = results["cpp"].stats.get("minimal_quorums")
            if got != want:
                mismatches.append(f"cpp minimal_quorums {got} != python {want}")
        if "frontier" in results and oracle.stats.get("best_node_fallback", 0) == 0:
            got = results["frontier"].stats.get("minimal_quorums")
            if got != want:
                mismatches.append(f"frontier minimal_quorums {got} != python {want}")

    return {"seed": seed, "kind": kind, "desc": desc,
            "engines": sorted(results), "max_scc": max_scc,
            "mismatches": mismatches}


def run_chaos_instance(seed: int, profile: str, workdir: pathlib.Path) -> dict:
    """Solve one instance under a seeded fault schedule on three auto-router
    configurations; the verdict must equal the fault-free sequential chain,
    or the failure must be a typed error — never a silent flip."""
    from quorum_intersection_tpu.backends.auto import AutoBackend, RungFailed
    from quorum_intersection_tpu.backends.base import OracleBudgetExceeded
    from quorum_intersection_tpu.pipeline import solve
    from quorum_intersection_tpu.utils import faults
    from quorum_intersection_tpu.utils.checkpoint import SweepCheckpoint

    kind, desc, data = make_instance(seed, profile)
    faults.clear_plan()
    expected = solve(data, backend=AutoBackend(race=False))

    class _InstantBurn:
        """Budgeted-oracle stand-in that burns immediately: forces the
        sequential chain onto the sweep rung so device faults actually fire
        (the real oracle answers these instances in microseconds, before
        any sweep fault point is reached)."""

        name = "burn"

        def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
            raise OracleBudgetExceeded("chaos: forced sweep rung")

    class SweepFirstAuto(AutoBackend):
        def _cpu_oracle(self, budget_s=None, cancel=None):
            if budget_s is not None:
                return _InstantBurn()
            return super()._cpu_oracle(budget_s=budget_s, cancel=cancel)

    configs = {
        "auto-seq": lambda: AutoBackend(race=False),
        "auto-race": lambda: AutoBackend(),
        "sweep-rung": lambda: SweepFirstAuto(
            race=False,
            checkpoint=SweepCheckpoint(workdir / f"chaos-{seed}.ckpt"),
        ),
    }
    mismatches: list = []
    typed_failures: list = []
    fired = 0
    schedule_label = faults.sample_plan(seed).label
    for name, make_backend in configs.items():
        # A fresh plan per configuration: hit counters start at zero, so
        # every chain sees the identical schedule (determinism contract).
        plan = faults.install_plan(faults.sample_plan(seed))
        try:
            res = solve(data, backend=make_backend())
            if res.intersects is not expected.intersects:
                mismatches.append(
                    f"{name}: SILENT verdict flip {res.intersects} != "
                    f"fault-free {expected.intersects} under {schedule_label}"
                )
        except (faults.FaultInjected, RungFailed) as exc:
            # Loud and typed: the acceptable failure shape.  Deliberately
            # NOT OSError: the hardened checkpoint writer swallows those,
            # so one escaping solve() is an unhardened path — a finding.
            typed_failures.append(f"{name}: {type(exc).__name__}: {exc}")
        except Exception as exc:  # noqa: BLE001 — an untyped crash IS a finding
            mismatches.append(
                f"{name}: UNTYPED crash {type(exc).__name__}: {exc} "
                f"under {schedule_label}"
            )
        finally:
            fired += len(plan.fired)
            faults.clear_plan()
    return {"seed": seed, "kind": kind, "desc": desc,
            "schedule": schedule_label, "fired": fired,
            "typed_failures": typed_failures, "mismatches": mismatches}


def chaos_main(args: argparse.Namespace) -> int:
    """--chaos driver: seeded fault schedules over the instance window."""
    # The watchdog is part of the hardened configuration under test: a
    # sampled native hang must degrade through it, not stall the soak.
    # (Explicit opt-in here, not a global default — production runs choose
    # their own deadline through the env registry.)
    os.environ.setdefault("QI_NATIVE_WATCHDOG_S", "0.25")
    t0 = time.time()
    bad: list = []
    total_fired = 0
    total_typed = 0
    with tempfile.TemporaryDirectory(prefix="qi-chaos-") as tmp:
        workdir = pathlib.Path(tmp)
        for i, seed in enumerate(range(args.seed, args.seed + args.instances)):
            rec = run_chaos_instance(seed, args.profile, workdir)
            total_fired += rec["fired"]
            total_typed += len(rec["typed_failures"])
            if rec["mismatches"]:
                bad.append(rec)
                print(f"CHAOS MISMATCH seed={seed} {rec['desc']} "
                      f"[{rec['schedule']}]: {rec['mismatches']}")
            if (i + 1) % 10 == 0:
                print(f"  ... {i + 1}/{args.instances} chaos instances "
                      f"({time.time() - t0:.0f}s, {len(bad)} mismatches, "
                      f"{total_fired} faults fired)", file=sys.stderr)
    summary = {
        "chaos": True,
        "window": [args.seed, args.seed + args.instances],
        "profile": args.profile,
        "instances": args.instances,
        "n_mismatches": len(bad),
        "mismatches": bad,
        "faults_fired": total_fired,
        "typed_failures": total_typed,
        "seconds": round(time.time() - t0, 1),
        "platform": os.environ.get("JAX_PLATFORMS", "ambient"),
    }
    print(json.dumps({k: v for k, v in summary.items() if k != "mismatches"}))
    if not args.no_ledger:
        ledger = load_ledger()
        ledger.setdefault("chaos_runs", []).append(summary)
        LEDGER.parent.mkdir(parents=True, exist_ok=True)
        LEDGER.write_text(json.dumps(ledger, indent=1))
        print(f"ledger: chaos run recorded -> {LEDGER}", file=sys.stderr)
    return 1 if bad else 0


def make_serve_traffic(seed: int, requests: int = 12):
    """Seed → ``(desc, [(request_id, snapshot), ...], oracle)``: a churn
    trace walked with temporal locality (the serving layer's realistic
    traffic shape) plus the fault-free ``python`` verdict per request —
    the parity bar every served or replayed verdict is held to."""
    from quorum_intersection_tpu.fbas import synth
    from quorum_intersection_tpu.pipeline import solve

    rng = random.Random(seed * 7919 + 17)
    broken = rng.random() < 0.4
    n = rng.randint(5, 9)
    base = synth.majority_fbas(n, broken=broken, prefix=f"SOAK{seed}")
    advance_every = rng.randint(2, 4)
    trace = synth.churn_trace(
        base, max(requests // advance_every, 1), seed=seed, max_diff=2,
    )
    stream, oracle, memo = [], {}, {}
    for i in range(requests):
        step = min(i // advance_every, len(trace) - 1)
        rid = f"soak-{seed}-{i}"
        stream.append((rid, trace[step]))
        if step not in memo:
            memo[step] = solve(trace[step], backend="python").intersects
        oracle[rid] = memo[step]
    return f"majority(n={n},broken={broken},churn)", stream, oracle


def run_serve_chaos_instance(seed: int, workdir: pathlib.Path,
                             chaos: bool, plan_spec: str = "") -> dict:
    """Drive one churn-trace stream through a live ServeEngine under a
    seeded serving-layer fault schedule; every request must reach exactly
    one outcome — the oracle verdict or a typed error — and a fault-free
    restart on the same journal must replay to oracle-identical verdicts.

    ``plan_spec`` replaces the sampled schedule with an explicit one
    (``QI_FAULTS`` syntax) — the guaranteed ``delta.diff`` mid-churn round
    uses it, since a sampled window may never draw a given point."""
    from quorum_intersection_tpu.serve import ServeEngine, ServeError
    from quorum_intersection_tpu.utils import faults

    desc, stream, oracle = make_serve_traffic(seed)
    journal = workdir / f"serve-chaos-{seed}{'-forced' if plan_spec else ''}.jsonl"
    faults.clear_plan()
    plan = None
    if plan_spec:
        plan = faults.install_plan(faults.parse_faults(plan_spec))
    elif chaos:
        plan = faults.install_plan(faults.sample_serve_plan(seed))
    schedule_label = plan.label if plan is not None else "fault-free"
    mismatches: list = []
    typed_failures: list = []
    served = 0
    rng = random.Random(seed * 104729 + 3)
    engine = ServeEngine(
        backend="python", journal=journal,
        batch_max=3, queue_depth=max(len(stream) // 2, 2),
    )
    tickets = []
    try:
        engine.start()
        for rid, snap in stream:
            # A sprinkle of tight deadlines exercises the expiry path; a
            # fast solve may still beat the budget — both outcomes are
            # legitimate, and both are checked below.
            deadline = 0.002 if rng.random() < 0.2 else None
            try:
                tickets.append(
                    (rid, engine.submit(snap, request_id=rid,
                                        deadline_s=deadline))
                )
            except (ServeError, faults.FaultInjected, OSError) as exc:
                typed_failures.append(f"{rid}: {type(exc).__name__}")
        engine.stop(drain=True, timeout=60.0)
    finally:
        faults.clear_plan()
    for rid, ticket in tickets:
        try:
            resp = ticket.result(timeout=30.0)
        except TimeoutError:
            mismatches.append(
                f"{rid}: SILENT DROP — no outcome 30s after drain "
                f"under {schedule_label}"
            )
            continue
        except (ServeError, faults.FaultInjected, OSError) as exc:
            typed_failures.append(f"{rid}: {type(exc).__name__}")
            continue
        except Exception as exc:  # noqa: BLE001 — an untyped crash IS a finding
            mismatches.append(
                f"{rid}: UNTYPED {type(exc).__name__}: {exc} "
                f"under {schedule_label}"
            )
            continue
        served += 1
        if resp.intersects is not oracle[rid]:
            mismatches.append(
                f"{rid}: SILENT verdict flip {resp.intersects} != "
                f"fault-free {oracle[rid]} under {schedule_label}"
            )
    # Fault-free restart on the same journal: whatever the chaos round
    # left un-done replays now, and a replayed verdict must still match
    # the oracle (journal faults may legitimately have lost entries — a
    # lost ENTRY is loud and allowed; a wrong VERDICT never is).
    engine2 = ServeEngine(backend="python", journal=journal, batch_max=3)
    try:
        report = engine2.start() or {}
        for rid, verdict in (report.get("verdicts") or {}).items():
            if rid in oracle and verdict is not oracle[rid]:
                mismatches.append(
                    f"{rid}: REPLAY verdict flip {verdict} != "
                    f"fault-free {oracle[rid]}"
                )
    finally:
        engine2.stop(drain=True, timeout=30.0)
    fired = len(plan.fired) if plan is not None else 0
    return {"seed": seed, "desc": desc, "schedule": schedule_label,
            "fired": fired, "served": served,
            "typed_failures": typed_failures, "mismatches": mismatches}


def run_serve_kill_replay(seed: int, workdir: pathlib.Path) -> dict:
    """Hard-kill a real serve subprocess mid-stream; the journal must
    replay with zero lost and zero duplicated verdicts, all oracle-equal.

    A ``serve.drain=hang`` rule (via ``QI_FAULTS``) holds every drain
    cycle ~0.3s so the kill provably lands with work in flight — without
    it the python oracle answers these topologies in microseconds and the
    kill would only ever hit an idle queue."""
    desc, stream, oracle = make_serve_traffic(seed)
    journal = workdir / f"serve-kill-{seed}.jsonl"
    mismatches: list = []
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "QI_FAULTS": "serve.drain=hang:0.3@1+",
        # The soak's own stream stays out of the child's telemetry files.
        "QI_METRICS_JSON": "", "QI_METRICS_PROM": "", "QI_TRACE_OUT": "",
    })
    child = subprocess.Popen(
        [sys.executable, "-m", "quorum_intersection_tpu", "serve",
         "--journal", str(journal), "--backend", "python",
         "--batch-max", "2"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, env=env, cwd=str(_REPO),
    )
    try:
        for rid, snap in stream:
            child.stdin.write(json.dumps(
                {"request_id": rid, "nodes": snap}
            ) + "\n")
        child.stdin.flush()
        # Kill only after the journal shows accepted work: a fixed sleep
        # can land before a slow machine's child even imported — the kill
        # would hit an empty journal and the round would pass vacuously.
        deadline = time.time() + 60.0
        while time.time() < deadline:
            try:
                text = journal.read_text()
            except OSError:
                text = ""
            if text.count('"kind": "req"') >= len(stream):
                break
            time.sleep(0.1)
        child.send_signal(signal.SIGKILL)
        out, _ = child.communicate(timeout=30)
    except subprocess.TimeoutExpired:
        child.kill()
        out, _ = child.communicate()
    responded = {}
    for line in out.splitlines():
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "verdict" in obj:
            responded[obj["request_id"]] = obj["verdict"]
    # Journal state at the kill: accepted (req) vs already-marked done.
    # Parsed directly (not through RequestJournal) so the soak stays an
    # independent witness of the on-disk format; only a torn FINAL line is
    # excused — that is the one artifact a hard kill may write.
    req_ids, done_ids = set(), set()
    try:
        lines = [ln for ln in journal.read_text().splitlines() if ln.strip()]
    except OSError:
        lines = []
    for i, line in enumerate(lines):
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            if i != len(lines) - 1:
                mismatches.append(f"corrupt journal line {i} (not the tail)")
            continue
        if obj.get("kind") == "req":
            req_ids.add(obj.get("request_id"))
        elif obj.get("kind") == "done":
            done_ids.add(obj.get("request_id"))
    # Restart: --replay-only re-solves everything accepted-but-not-done.
    env_replay = dict(env)
    env_replay["QI_FAULTS"] = ""
    replay_proc = subprocess.run(
        [sys.executable, "-m", "quorum_intersection_tpu", "serve",
         "--journal", str(journal), "--backend", "python", "--replay-only"],
        capture_output=True, text=True, env=env_replay, cwd=str(_REPO),
        timeout=120,
    )
    report = {}
    for line in replay_proc.stdout.splitlines():
        try:
            obj = json.loads(line)
        except json.JSONDecodeError:
            continue
        if obj.get("kind") == "replay":
            report = obj
    replayed = dict(report.get("verdicts") or {})
    failed = set(report.get("errors") or {})
    if replay_proc.returncode != 0:
        mismatches.append(f"replay exited {replay_proc.returncode}")
    # Zero lost: every accepted request reached an outcome on one side of
    # the kill.  Zero duplicated: nothing marked done was re-replayed.
    lost = req_ids - done_ids - set(replayed) - failed
    if lost:
        mismatches.append(f"LOST requests (no outcome across kill): {sorted(lost)}")
    dup = done_ids & set(replayed)
    if dup:
        mismatches.append(f"DUPLICATED verdicts (done yet replayed): {sorted(dup)}")
    for rid, verdict in responded.items():
        if rid in oracle and verdict is not oracle[rid]:
            mismatches.append(
                f"{rid}: pre-kill verdict {verdict} != oracle {oracle[rid]}")
    for rid, verdict in replayed.items():
        if rid in oracle and verdict is not oracle[rid]:
            mismatches.append(
                f"{rid}: replayed verdict {verdict} != oracle {oracle[rid]}")
    return {"seed": seed, "desc": desc, "accepted": len(req_ids),
            "responded_pre_kill": len(responded), "replayed": len(replayed),
            "already_done": len(done_ids), "mismatches": mismatches}


def serve_soak_main(args: argparse.Namespace) -> int:
    """--serve driver: serving-layer chaos + kill-and-replay per seed."""
    t0 = time.time()
    bad: list = []
    total_fired = 0
    total_typed = 0
    total_served = 0
    kill_rounds = 0
    with tempfile.TemporaryDirectory(prefix="qi-serve-soak-") as tmp:
        workdir = pathlib.Path(tmp)
        for i, seed in enumerate(range(args.seed, args.seed + args.instances)):
            rec = run_serve_chaos_instance(seed, workdir, chaos=args.chaos)
            total_fired += rec["fired"]
            total_typed += len(rec["typed_failures"])
            total_served += rec["served"]
            if rec["mismatches"]:
                bad.append(rec)
                print(f"SERVE CHAOS MISMATCH seed={seed} {rec['desc']} "
                      f"[{rec['schedule']}]: {rec['mismatches']}")
            # Guaranteed qi-delta degradation round (ISSUE 9): the sampled
            # window may never draw delta.diff, so every odd chaos seed
            # re-runs its stream with the differ failing from the second
            # drain batch on — degraded mid-churn, the engine must fall
            # back to full re-solves with verdicts still oracle-identical.
            if args.chaos and seed % 2 == 1:
                drec = run_serve_chaos_instance(
                    seed, workdir, chaos=True,
                    plan_spec="delta.diff=error@2+",
                )
                total_fired += drec["fired"]
                total_served += drec["served"]
                if not drec["fired"]:
                    drec["mismatches"].append(
                        "forced delta.diff plan never fired "
                        "(differ path not reached mid-churn)"
                    )
                if drec["mismatches"]:
                    bad.append(drec)
                    print(f"SERVE DELTA-FAULT MISMATCH seed={seed} "
                          f"{drec['desc']}: {drec['mismatches']}")
            # The kill round costs a subprocess pair; every other seed
            # keeps the soak's wall time linear in --instances.
            if seed % 2 == 0:
                kill_rounds += 1
                krec = run_serve_kill_replay(seed, workdir)
                if krec["mismatches"]:
                    bad.append(krec)
                    print(f"SERVE KILL-REPLAY MISMATCH seed={seed} "
                          f"{krec['desc']}: {krec['mismatches']}")
            if (i + 1) % 5 == 0:
                print(f"  ... {i + 1}/{args.instances} serve instances "
                      f"({time.time() - t0:.0f}s, {len(bad)} mismatches, "
                      f"{total_fired} faults fired)", file=sys.stderr)
    summary = {
        "serve": True,
        "chaos": bool(args.chaos),
        "window": [args.seed, args.seed + args.instances],
        "instances": args.instances,
        "kill_rounds": kill_rounds,
        "n_mismatches": len(bad),
        "mismatches": bad,
        "faults_fired": total_fired,
        "typed_failures": total_typed,
        "served": total_served,
        "seconds": round(time.time() - t0, 1),
        "platform": os.environ.get("JAX_PLATFORMS", "ambient"),
    }
    print(json.dumps({k: v for k, v in summary.items() if k != "mismatches"}))
    if not args.no_ledger:
        ledger = load_ledger()
        ledger.setdefault("serve_runs", []).append(summary)
        LEDGER.parent.mkdir(parents=True, exist_ok=True)
        LEDGER.write_text(json.dumps(ledger, indent=1))
        print(f"ledger: serve run recorded -> {LEDGER}", file=sys.stderr)
    return 1 if bad else 0


def run_fleet_chaos_instance(seed: int, workdir: pathlib.Path,
                             chaos: bool) -> dict:
    """Drive one churn-trace stream through a live 2-worker fleet under a
    seeded fleet-tier fault schedule (``utils/faults.py
    sample_fleet_plan`` — routing, probing, failover replay and the
    shared store tier are all drawable), with a kill-one-of-N round on
    even seeds; every request must reach exactly one outcome — the
    oracle verdict or a typed error — across routing degrades, a dead
    worker's journal failover, and a dead shared store tier."""
    from quorum_intersection_tpu.fleet import FleetEngine
    from quorum_intersection_tpu.serve import ServeError
    from quorum_intersection_tpu.utils import faults

    desc, stream, oracle = make_serve_traffic(seed)
    faults.clear_plan()
    plan = (
        faults.install_plan(faults.sample_fleet_plan(seed)) if chaos else None
    )
    schedule_label = plan.label if plan is not None else "fault-free"
    mismatches: list = []
    typed_failures: list = []
    served = 0
    killed = False
    engine = FleetEngine(
        2, backend="python", worker_mode="local",
        journal_dir=workdir / f"fleet-{seed}", probe_interval_s=0.2,
        batch_max=3,
    )
    tickets = []
    try:
        engine.start()
        kill_at = len(stream) // 2 if seed % 2 == 0 else None
        for i, (rid, snap) in enumerate(stream):
            if kill_at is not None and i == kill_at and engine.worker_ids():
                engine.kill_worker(engine.worker_ids()[0], evict=True)
                killed = True
            try:
                tickets.append((rid, engine.submit(snap, request_id=rid)))
            except (ServeError, faults.FaultInjected, OSError) as exc:
                typed_failures.append(f"{rid}: {type(exc).__name__}")
        for rid, ticket in tickets:
            try:
                resp = ticket.result(timeout=60.0)
            except TimeoutError:
                mismatches.append(
                    f"{rid}: SILENT DROP — no outcome 60s after submit "
                    f"under {schedule_label}"
                )
                continue
            except (ServeError, faults.FaultInjected, OSError) as exc:
                typed_failures.append(f"{rid}: {type(exc).__name__}")
                continue
            except Exception as exc:  # noqa: BLE001 — an untyped crash IS a finding
                mismatches.append(
                    f"{rid}: UNTYPED {type(exc).__name__}: {exc} "
                    f"under {schedule_label}"
                )
                continue
            served += 1
            if resp.intersects is not oracle[rid]:
                mismatches.append(
                    f"{rid}: SILENT verdict flip {resp.intersects} != "
                    f"fault-free {oracle[rid]} under {schedule_label}"
                )
    finally:
        engine.stop(drain=True, timeout=60.0)
        faults.clear_plan()
    fired = len(plan.fired) if plan is not None else 0
    return {"seed": seed, "desc": desc, "schedule": schedule_label,
            "fired": fired, "served": served, "killed_one": killed,
            "typed_failures": typed_failures, "mismatches": mismatches}


def run_mesh_chaos_instance(seed: int, workdir: pathlib.Path,
                            chaos: bool) -> dict:
    """Socket-mesh round (qi-mesh, ISSUE 19): a REAL ``serve --socket``
    subprocess joined over TCP as worker ``j0`` next to one local worker,
    streamed under a seeded wire-tier fault schedule
    (``utils/faults.py sample_mesh_plan`` — join, lease and journal ship
    are drawable).  Even seeds SIGSTOP the peer mid-stream (a PARTITION,
    not a death: suspicion + hedged dispatch keep its arc answering) and
    SIGCONT it afterwards (the rejoin path).  Every admitted request must
    reach exactly one outcome — the oracle verdict or a typed error."""
    from quorum_intersection_tpu.fleet import FleetEngine
    from quorum_intersection_tpu.serve import ServeError
    from quorum_intersection_tpu.utils import faults

    desc, stream, oracle = make_serve_traffic(seed, requests=8)
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu", "PYTHONUNBUFFERED": "1",
        "QI_METRICS_JSON": "", "QI_METRICS_PROM": "", "QI_TRACE_OUT": "",
    })
    child = subprocess.Popen(
        [sys.executable, "-u", "-m", "quorum_intersection_tpu", "serve",
         "--socket", "0", "--backend", "python", "--emit-certs",
         "--journal", str(workdir / f"mesh-{seed}.journal")],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True, env=env, cwd=str(_REPO),
    )
    mismatches: list = []
    typed_failures: list = []
    served = 0
    fired = 0
    partitioned = False
    schedule_label = "fault-free"
    try:
        port = None
        deadline = time.time() + 120.0
        while time.time() < deadline:
            line = child.stdout.readline()
            if not line:
                break
            try:
                obj = json.loads(line)
            except json.JSONDecodeError:
                continue
            if obj.get("kind") == "listening":
                port = int(obj["port"])
                break
        if port is None:
            return {"seed": seed, "desc": desc, "schedule": schedule_label,
                    "fired": 0, "served": 0, "partitioned": False,
                    "mesh": True, "typed_failures": [],
                    "mismatches": ["serve --socket peer never announced "
                                   "its port"]}
        faults.clear_plan()
        plan = (
            faults.install_plan(faults.sample_mesh_plan(seed))
            if chaos else None
        )
        schedule_label = plan.label if plan is not None else "fault-free"
        engine = FleetEngine(
            1, backend="python", worker_mode="local",
            journal_dir=workdir / f"mesh-fleet-{seed}",
            probe_interval_s=0.2, respawn_max=0,
            joins=[f"127.0.0.1:{port}"],
        )
        tickets = []
        try:
            # A join-faulted start degrades to standalone (the local
            # worker keeps serving) — that IS the contract under test.
            engine.start()
            stall_at = len(stream) // 2 if seed % 2 == 0 else None
            for i, (rid, snap) in enumerate(stream):
                if (stall_at is not None and i == stall_at
                        and child.poll() is None):
                    os.kill(child.pid, signal.SIGSTOP)
                    partitioned = True
                try:
                    tickets.append((rid, engine.submit(snap, request_id=rid)))
                except (ServeError, faults.FaultInjected, OSError) as exc:
                    typed_failures.append(f"{rid}: {type(exc).__name__}")
            if partitioned:
                # Long enough for missed probes to SUSPECT the peer (its
                # requests hedge to the next arc owner), short of its
                # lease — then the partition heals and it rejoins.
                time.sleep(0.8)
                os.kill(child.pid, signal.SIGCONT)
            for rid, ticket in tickets:
                try:
                    resp = ticket.result(timeout=60.0)
                except TimeoutError:
                    mismatches.append(
                        f"{rid}: SILENT DROP — no outcome 60s after submit "
                        f"under {schedule_label}"
                    )
                    continue
                except (ServeError, faults.FaultInjected, OSError) as exc:
                    typed_failures.append(f"{rid}: {type(exc).__name__}")
                    continue
                except Exception as exc:  # noqa: BLE001 — an untyped crash IS a finding
                    mismatches.append(
                        f"{rid}: UNTYPED {type(exc).__name__}: {exc} "
                        f"under {schedule_label}"
                    )
                    continue
                served += 1
                if resp.intersects is not oracle[rid]:
                    mismatches.append(
                        f"{rid}: SILENT verdict flip {resp.intersects} != "
                        f"fault-free {oracle[rid]} under {schedule_label}"
                    )
        finally:
            engine.stop(drain=True, timeout=60.0)
            fired = len(plan.fired) if plan is not None else 0
            faults.clear_plan()
    finally:
        try:
            if child.poll() is None:
                os.kill(child.pid, signal.SIGCONT)  # never leave it stopped
                child.stdin.close()
                child.wait(timeout=30.0)
        except (OSError, subprocess.TimeoutExpired):
            child.kill()
    return {"seed": seed, "desc": desc, "schedule": schedule_label,
            "fired": fired, "served": served, "partitioned": partitioned,
            "mesh": True, "typed_failures": typed_failures,
            "mismatches": mismatches}


def fleet_soak_main(args: argparse.Namespace) -> int:
    """--fleet driver: fleet-tier chaos (+ kill-one-of-N) per seed."""
    t0 = time.time()
    bad: list = []
    total_fired = 0
    total_typed = 0
    total_served = 0
    kill_rounds = 0
    mesh_rounds = 0
    partition_rounds = 0
    with tempfile.TemporaryDirectory(prefix="qi-fleet-soak-") as tmp:
        workdir = pathlib.Path(tmp)
        for i, seed in enumerate(range(args.seed, args.seed + args.instances)):
            rec = run_fleet_chaos_instance(seed, workdir, chaos=args.chaos)
            total_fired += rec["fired"]
            total_typed += len(rec["typed_failures"])
            total_served += rec["served"]
            kill_rounds += int(rec["killed_one"])
            if rec["mismatches"]:
                bad.append(rec)
                print(f"FLEET CHAOS MISMATCH seed={seed} {rec['desc']} "
                      f"[{rec['schedule']}]: {rec['mismatches']}")
            # Socket-mesh round (qi-mesh, ISSUE 19): a real --join peer
            # under wire-tier chaos; even seeds get a SIGSTOP/SIGCONT
            # partition (suspect → hedge → rejoin), never a kill.
            if args.chaos:
                mesh_rounds += 1
                mrec = run_mesh_chaos_instance(seed, workdir,
                                               chaos=args.chaos)
                total_fired += mrec["fired"]
                total_typed += len(mrec["typed_failures"])
                total_served += mrec["served"]
                partition_rounds += int(mrec["partitioned"])
                if mrec["mismatches"]:
                    bad.append(mrec)
                    print(f"MESH CHAOS MISMATCH seed={seed} {mrec['desc']} "
                          f"[{mrec['schedule']}]: {mrec['mismatches']}")
            if (i + 1) % 5 == 0:
                print(f"  ... {i + 1}/{args.instances} fleet instances "
                      f"({time.time() - t0:.0f}s, {len(bad)} mismatches, "
                      f"{total_fired} faults fired)", file=sys.stderr)
    summary = {
        "fleet": True,
        "chaos": bool(args.chaos),
        "window": [args.seed, args.seed + args.instances],
        "instances": args.instances,
        "kill_rounds": kill_rounds,
        "mesh_rounds": mesh_rounds,
        "partition_rounds": partition_rounds,
        "n_mismatches": len(bad),
        "mismatches": bad,
        "faults_fired": total_fired,
        "typed_failures": total_typed,
        "served": total_served,
        "seconds": round(time.time() - t0, 1),
        "platform": os.environ.get("JAX_PLATFORMS", "ambient"),
    }
    print(json.dumps({k: v for k, v in summary.items() if k != "mismatches"}))
    if not args.no_ledger:
        ledger = load_ledger()
        ledger.setdefault("fleet_runs", []).append(summary)
        LEDGER.parent.mkdir(parents=True, exist_ok=True)
        LEDGER.write_text(json.dumps(ledger, indent=1))
        print(f"ledger: fleet run recorded -> {LEDGER}", file=sys.stderr)
    return 1 if bad else 0


def load_ledger() -> dict:
    if LEDGER.exists():
        return json.loads(LEDGER.read_text())
    return {"totals": {"instances": 0, "mismatches": 0, "by_generator": {}},
            "runs": []}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--instances", type=int, default=40)
    parser.add_argument("--seed", type=int, default=0, help="first seed of the window")
    parser.add_argument("--no-ledger", action="store_true",
                        help="run without recording to the ledger")
    parser.add_argument("--force", action="store_true",
                        help="re-run a window the ledger already records")
    parser.add_argument("--profile", choices=("small", "large"), default="small",
                        help="large: routing-relevant SCC sizes (14-20); slower "
                             "per instance, soaks spill + batched-flag paths")
    parser.add_argument("--platform", choices=("cpu", "ambient"), default="cpu",
                        help="cpu (default): pin jax to the host CPU so a dead "
                             "tunnel can never hang the soak; ambient: use "
                             "whatever JAX_PLATFORMS/the image selects (chip)")
    parser.add_argument("--chaos", action="store_true",
                        help="solve each instance under a seeded fault "
                             "schedule (utils/faults.py) and assert the "
                             "verdict equals the fault-free sequential chain "
                             "or fails loudly with a typed error")
    parser.add_argument("--fleet", action="store_true",
                        help="soak the replicated fleet tier (fleet.py): "
                             "churn-trace streams through a live 2-worker "
                             "fleet (with --chaos: under seeded fleet.* "
                             "fault schedules — routing, probing, failover "
                             "replay, shared store) plus a kill-one-of-N "
                             "round per even seed and, with --chaos, a "
                             "socket-mesh round per seed (a real serve "
                             "--socket peer joined over TCP under seeded "
                             "fleet.{join,lease,ship} schedules, with a "
                             "SIGSTOP/SIGCONT partition on even seeds); "
                             "oracle-equal verdicts or typed errors only, "
                             "zero lost / zero duplicated across the kill "
                             "and the partition")
    parser.add_argument("--serve", action="store_true",
                        help="soak the serving layer (serve.py) instead of "
                             "one-shot solves: churn-trace streams through a "
                             "live ServeEngine (with --chaos: under seeded "
                             "serve.* fault schedules) plus a SIGKILL "
                             "mid-stream + journal-replay round per even "
                             "seed; oracle-equal verdicts or typed errors "
                             "only, zero lost / zero duplicated across the "
                             "kill")
    args = parser.parse_args(argv)

    # The differential contract is platform-independent, so the harness
    # defaults to the host CPU — an explicit pin, because this image's
    # ambient env exports JAX_PLATFORMS=axon and a soft default would lose.
    if args.platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
        from quorum_intersection_tpu.utils.platform import honor_platform_env

        honor_platform_env()

    if args.fleet:
        return fleet_soak_main(args)
    if args.serve:
        return serve_soak_main(args)
    if args.chaos:
        return chaos_main(args)

    ledger = load_ledger()
    window = [args.seed, args.seed + args.instances]
    if not args.force and not args.no_ledger:
        for run in ledger["runs"]:
            if (run["window"] == window
                    and run.get("profile", "small") == args.profile):
                print(f"window {window} already recorded ({run['instances']} "
                      f"instances, {run['n_mismatches']} mismatches); use "
                      f"--force to re-run or pick a fresh --seed", file=sys.stderr)
                return 0

    t0 = time.time()
    by_gen: dict = {}
    bad: list = []
    for i, seed in enumerate(range(*window)):
        rec = run_instance(seed, args.profile)
        by_gen[rec["kind"]] = by_gen.get(rec["kind"], 0) + 1
        if rec["mismatches"]:
            bad.append(rec)
            print(f"MISMATCH seed={seed} {rec['desc']}: {rec['mismatches']}")
        if (i + 1) % 10 == 0:
            print(f"  ... {i + 1}/{args.instances} "
                  f"({time.time() - t0:.0f}s, {len(bad)} mismatches)",
                  file=sys.stderr)

    elapsed = time.time() - t0
    summary = {
        "window": window,
        "profile": args.profile,
        "instances": args.instances,
        "n_mismatches": len(bad),
        "mismatches": bad,
        "by_generator": by_gen,
        "seconds": round(elapsed, 1),
        "platform": os.environ.get("JAX_PLATFORMS", "ambient"),
    }
    print(json.dumps({k: v for k, v in summary.items() if k != "mismatches"}))

    if not args.no_ledger:
        ledger["runs"].append(summary)
        totals = ledger["totals"]
        totals["instances"] += args.instances
        totals["mismatches"] += len(bad)
        for k, v in by_gen.items():
            totals["by_generator"][k] = totals["by_generator"].get(k, 0) + v
        LEDGER.parent.mkdir(parents=True, exist_ok=True)
        LEDGER.write_text(json.dumps(ledger, indent=1))
        print(f"ledger: {totals['instances']} cumulative instances, "
              f"{totals['mismatches']} mismatches -> {LEDGER}", file=sys.stderr)
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
