"""Seed-controlled randomized fuzz of the ASan/UBSan-instrumented native
CLI — volume evidence beyond the suite's fixed hostile corpus.

The suite (tests/test_sanitizers.py, tests/test_hostile_input.py) pins a
curated corpus: golden fixtures, deep nesting, junk unicode, truncation.
This runner generates THOUSANDS of fresh cases per window and drives every
one through the sanitized binary (`build_native_cli(sanitize=True)`,
ASan + UBSan with -fno-sanitize-recover):

- **mutated**: a valid synthetic FBAS (every generator family), serialized
  then damaged — truncated at a random byte, random byte flips, junk
  splices, randomly injected tokens.  Contract: exit 0/1 with a verdict OR
  a clean `invalid FBAS configuration:` rejection — never a crash, never a
  sanitizer report.
- **random-json**: structurally random JSON-ish blobs (arrays/objects/
  numbers/strings with hostile shapes).  Same contract.
- **valid**: the undamaged serialization.  When the drawn flag set
  preserves verdict semantics (none / -v / -t / --seed — compat,
  alias0 and --scope-scc legitimately change it), the contract
  additionally includes VERDICT PARITY with the Python pipeline
  (`pipeline.solve`, cpp engine).

Each case runs under a randomly drawn FLAG SET (none / -v / -t / -p /
-g / --compat / --seed N / combinations): the PageRank, Graphviz,
trace, and compat code paths see the same hostile inputs as the verdict
path — the curated suite exercises them on fixtures only.  Output
contracts per mode: a verdict, a clean rejection, or mode-specific
output (PageRank listing / DOT graph) — never a crash, never a
sanitizer report.

Every window appends to ``benchmarks/results/fuzz_native_ledger.json`` so
the cumulative case count grows round over round, soak-style.  Re-running
a recorded (seed, cases) window is skipped unless --force.

Usage::

    python tools/fuzz_native.py                    # 1500 cases from seed 0
    python tools/fuzz_native.py --cases 3000 --seed 7000
"""

from __future__ import annotations

import argparse
import json
import pathlib
import random
import re
import subprocess
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

LEDGER = pathlib.Path(__file__).resolve().parent.parent / (
    "benchmarks/results/fuzz_native_ledger.json"
)

SANITIZER_MARKERS = (
    "AddressSanitizer",
    "UndefinedBehaviorSanitizer",
    "runtime error:",
    "LeakSanitizer",
)


def make_valid(rng: random.Random) -> str:
    """One valid synthetic FBAS, any generator family, serialized."""
    from quorum_intersection_tpu.fbas.synth import (
        benchmark_fbas,
        hierarchical_fbas,
        majority_fbas,
        random_fbas,
        stellar_like_fbas,
    )

    kind = rng.randrange(5)
    broken = rng.random() < 0.3
    if kind == 0:
        data = majority_fbas(rng.randrange(3, 14), broken=broken)
    elif kind == 1:
        data = hierarchical_fbas(rng.randrange(2, 5), rng.randrange(3, 5),
                                 broken=broken)
    elif kind == 2:
        data = random_fbas(rng.randrange(4, 16), seed=rng.randrange(10**6),
                           nested_prob=rng.random() * 0.5)
    elif kind == 3:
        data = stellar_like_fbas(n_core_orgs=rng.randrange(3, 6),
                                 n_watchers=rng.randrange(0, 12))
    else:
        n_total = rng.randrange(8, 40)
        data = benchmark_fbas(n_total, rng.randrange(4, min(12, n_total)),
                              broken=broken, seed=rng.randrange(10**6))
    return json.dumps(data)


def mutate(rng: random.Random, text: str) -> str:
    """Damage a serialized FBAS in one of several byte/token-level ways."""
    mode = rng.randrange(8)
    if mode == 0 and len(text) > 2:  # truncate
        return text[: rng.randrange(1, len(text))]
    if mode == 1:  # byte flips
        b = bytearray(text.encode())
        for _ in range(rng.randrange(1, 8)):
            b[rng.randrange(len(b))] = rng.randrange(256)
        return b.decode("utf-8", errors="replace")
    if mode == 2:  # junk splice
        pos = rng.randrange(len(text))
        junk = rng.choice(['{{{{', '\\u0000', '"' * 50, '\xff\xfe',
                           '9' * 400, '[[[[', 'null,' * 30])
        return text[:pos] + junk + text[pos:]
    if mode == 3:  # token injection: duplicate / rename a key
        return text.replace('"threshold"', rng.choice(
            ['"threshold": 1e308, "threshold"', '"THRESHOLD"',
             '"threshold\\u0000"']), 1)
    if mode == 4:  # wrap in garbage
        return rng.choice(['x', '[', '{"a":']) + text
    if mode == 5:  # duplicate a whole node object (duplicate publicKey)
        try:
            arr = json.loads(text)
            arr.append(arr[rng.randrange(len(arr))])
            return json.dumps(arr)
        except Exception:
            return text + text
    if mode == 6:  # numeric extremes on thresholds
        repl = rng.choice(['-2147483649', '2147483648', '9' * 25,
                           '1e309', '-0', '0.5'])
        if rng.random() < 0.5:
            # First threshold only: the extreme lands as the value and the
            # original number is demoted to an ignored "x" key.
            return text.replace(
                '"threshold": ', '"threshold": ' + repl + ' , "x": ', 1
            )
        # Every threshold in the document.
        return re.sub(r'"threshold": \d+', '"threshold": ' + repl, text)
    # mode 7: blow up a validators array
    return text.replace('"validators": [', '"validators": [' +
                        ('"V", ' * rng.randrange(1, 2000)), 1)


def make_random_json(rng: random.Random) -> str:
    """Structurally random JSON-ish blob with hostile shapes."""
    choice = rng.randrange(6)
    if choice == 0:
        return "[" * rng.randrange(1, 200)
    if choice == 1:
        return json.dumps([{"publicKey": "K" * rng.randrange(1, 300),
                            "quorumSet": {"threshold": rng.randrange(-5, 5),
                                          "validators": []}}] * rng.randrange(1, 5))
    if choice == 2:
        return json.dumps({"a": [rng.random() for _ in range(rng.randrange(50))]})
    if choice == 3:
        return '[{"publicKey": %s}]' % rng.choice(
            ['123', 'null', 'true', '{"x": 1}', '[1,2]'])
    if choice == 4:
        n = rng.randrange(1, 60)
        return ('[{"publicKey": "A", "quorumSet": ' +
                '{"threshold": 1, "innerQuorumSets": [' * n +
                '{}' + ']}' * n + '}]')
    return ''.join(rng.choice('[]{}",:0123456789nulltrue \n') for _ in
                   range(rng.randrange(1, 500)))


FLAG_SETS = (
    [], [], [],  # bare verdict path, weighted
    ["-v"], ["-t"], ["-p"], ["-g"], ["--compat"], ["--compat", "-v"],
    ["--seed", "7"], ["-v", "-t"], ["--scope-scc"],
    ["--dangling-policy", "alias0"],
)

# Flag sets that must not change the verdict on a valid FBAS: verbosity /
# tracing only affect diagnostics, and the randomized tie-break is
# verdict-independent by design (SURVEY C7).  compat / alias0 /
# --scope-scc deliberately change semantics (PARITY.md deviations) and
# are excluded from the parity oracle.
SEMANTICS_PRESERVING = ({"-v", "-t"}, {"--seed", "7"})


def preserves_semantics(flags) -> bool:
    f = set(flags)
    return any(f <= allowed for allowed in SEMANTICS_PRESERVING)


def run_case(cli: str, payload: str, flags) -> tuple:
    proc = subprocess.run(
        [cli, *flags], input=payload, capture_output=True, text=True,
        timeout=120,
    )
    sanitizer = any(m in proc.stderr for m in SANITIZER_MARKERS)
    return proc, sanitizer


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--cases", type=int, default=1500)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--force", action="store_true")
    parser.add_argument("--no-ledger", action="store_true")
    args = parser.parse_args()

    from quorum_intersection_tpu.backends.cpp import build_native_cli

    cli = str(build_native_cli(sanitize=True))

    ledger = {"windows": [], "cumulative_cases": 0, "failures": []}
    if LEDGER.exists():
        ledger = json.loads(LEDGER.read_text())
    window_key = [args.seed, args.cases]
    if not args.force and any(
        w["window"] == window_key for w in ledger["windows"]
    ):
        print(f"window {window_key} already recorded; --force to redo")
        return 0

    rng = random.Random(args.seed)
    t0 = time.time()
    counts = {"valid": 0, "mutated": 0, "random-json": 0}
    failures = []
    parity_checked = 0
    for i in range(args.cases):
        roll = rng.random()
        if roll < 0.2:
            kind, payload = "valid", make_valid(rng)
        elif roll < 0.7:
            kind, payload = "mutated", mutate(rng, make_valid(rng))
        else:
            kind, payload = "random-json", make_random_json(rng)
        counts[kind] += 1
        flags = rng.choice(FLAG_SETS)
        try:
            proc, sanitizer = run_case(cli, payload, flags)
        except subprocess.TimeoutExpired:
            failures.append({"case": i, "kind": kind, "flags": flags,
                             "why": "timeout 120s",
                             "payload_head": payload[:200]})
            continue
        ok_exit = proc.returncode in (0, 1)
        clean_reject = proc.stdout.startswith("invalid FBAS configuration:") \
            or proc.stderr.startswith("invalid FBAS configuration:")
        out_lines = proc.stdout.strip().splitlines()
        if flags:
            # Verbose/trace modes print diagnostics above the verdict line.
            verdict = bool(out_lines) and out_lines[-1] in ("true", "false")
        else:
            # The bare verdict path must print EXACTLY the verdict: a
            # corrupted default-path print (stray diagnostic, double
            # print) must fail even when it happens to end in a verdict.
            verdict = proc.stdout.strip() in ("true", "false")
        mode_output = (
            ("-p" in flags and "PageRank" in proc.stdout)
            or ("-g" in flags and "digraph" in proc.stdout)
        )
        if sanitizer or not ok_exit or not (verdict or clean_reject
                                            or mode_output):
            failures.append({
                "case": i, "kind": kind, "rc": proc.returncode,
                "flags": flags,
                "sanitizer": sanitizer, "stdout_head": proc.stdout[:200],
                "stderr_head": proc.stderr[:300],
                "payload_head": payload[:200],
            })
            continue
        if kind == "valid" and verdict and preserves_semantics(flags):
            # Verdict parity with the Python pipeline on undamaged inputs.
            from quorum_intersection_tpu.pipeline import solve

            want = solve(payload, backend="cpp").intersects
            got = out_lines[-1] == "true"
            parity_checked += 1
            if want is not got:
                failures.append({
                    "case": i, "kind": "valid-PARITY", "native": got,
                    "python_pipeline": want, "payload_head": payload[:300],
                })
        if (i + 1) % 200 == 0:
            print(f"  ... {i + 1}/{args.cases} "
                  f"({time.time() - t0:.0f}s, {len(failures)} failures)",
                  flush=True)

    record = {
        "window": window_key, "cases": args.cases, "by_kind": counts,
        "parity_checked": parity_checked, "n_failures": len(failures),
        "seconds": round(time.time() - t0, 1),
    }
    print(json.dumps(record), flush=True)
    for f in failures[:20]:
        print("FAILURE:", json.dumps(f), flush=True)
    if not args.no_ledger:
        ledger["windows"].append(record)
        ledger["cumulative_cases"] += args.cases
        ledger["failures"].extend(failures)
        LEDGER.write_text(json.dumps(ledger, indent=1))
        print(f"ledger: {ledger['cumulative_cases']} cumulative cases, "
              f"{len(ledger['failures'])} failures -> {LEDGER}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
