#!/usr/bin/env python3
"""qi-cert/1 independent certificate checker (ISSUE 7 tentpole, piece 3).

Re-validates a verdict certificate against the RAW stellarbeat JSON it was
produced from, with a minimal quorum-set evaluator of its own — deliberately
**stdlib-only and import-free of the quorum_intersection_tpu package**: the
whole point is an adversarial counterpart that shares no code with the
engines it audits, so a bug in the package's semantics cannot vouch for
itself.

What is checked:

- schema + structural sanity (`qi-cert/1`, known node ids, sizes);
- the **guard claim**: this checker builds its own trust graph (validators
  at every nesting depth; `strict` dangling drops unknown refs, `alias0`
  aliases them to vertex 0 — the certificate records which policy the
  verdict used), runs its own iterative Tarjan, scans every SCC for a
  contained quorum with its own greatest-fixpoint evaluator, and compares
  the quorum-bearing count against the certificate's;
- a **false** verdict: the witness pair must be two nonempty, disjoint,
  self-contained quorums (every member's slice satisfied within its own
  quorum — Q2 null qsets never satisfy, Q3 degenerate/unreachable
  thresholds never satisfy, Q4 self-availability), and the certificate's
  per-member evidence must agree with this checker's own evaluation; a
  false verdict WITHOUT a witness must claim `no_quorum`, which is
  verified by the graph-wide greatest fixpoint coming up empty;
- a **true** verdict: exactly one quorum-bearing SCC; the coverage
  ledger's SCC must be that SCC (under the default `quorum-bearing`
  selection); every sweep ledger entry must satisfy the arithmetic
  invariant `enumerated + pruned_guard + skipped_pack_fill + cancelled
  [+ resumed_prefix] == window_space == 2^(size-1)` with `cancelled == 0`
  and `skipped_pack_fill == 0` (a cancelled or skipped window cannot
  support an exhaustive-coverage claim; a checkpoint-resumed run's
  fingerprint-matched prefix counts without inflating the run's own
  enumerated windows); B&B entries (native/python oracle) must
  carry `bnb_calls >= 1`, frontier entries `frontier_chunks_drained >= 1`;
- **pruned mass** (ISSUE 10): nonzero `windows_pruned_guard` must be
  backed by a `pruned_blocks` ledger `{k, rule, prefixes}` plus an
  `enumeration` block naming the fixed-out node and the bit→node map —
  a permutation of the entry's SCC.  For every pruned block this checker
  rebuilds the block's MAXIMAL candidate (all free low-bit nodes plus
  the prefix's fixed-one nodes) and re-runs its own greatest fixpoint on
  it: the `empty-max-quorum` rule is sound iff that fixpoint is empty
  (the fixpoint is monotone in its candidate set, so no window of the
  block can contain a quorum, hence none can hit).  All blocks are
  re-verified by default; `--sample N` checks a deterministic stride of
  N blocks for huge ledgers.  Pruned mass without a verifiable block
  ledger, an unknown rule, a count mismatch, or a block whose maximal
  candidate DOES contain a quorum is unsound.

Exit codes: 0 — certificate sound; 1 — any unsound witness, ledger
arithmetic failure, or guard mismatch; 2 — unreadable/ill-formed inputs.

Usage::

    python tools/check_cert.py CERT.json FBAS.json [-q] [--sample N]
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Sequence, Set, Tuple

MAX_DEPTH = 128  # D9: mirror the package's nesting cap, reject deeper


class CheckFailure(Exception):
    """One unsound certificate claim (exit 1)."""


class InputError(Exception):
    """Unreadable or structurally ill-formed input (exit 2)."""


# ---------------------------------------------------------------------------
# Minimal FBAS front end (independent re-implementation, stdlib only)


def _threshold(raw: object) -> Optional[int]:
    """Normalize a threshold field: ints and numeric strings (ptree
    compat) are accepted; anything else is ill-formed."""
    if isinstance(raw, bool) or raw is None:
        raise InputError(f"malformed threshold {raw!r}")
    if isinstance(raw, int):
        return raw
    if isinstance(raw, str):
        try:
            return int(raw)
        except ValueError:
            raise InputError(f"malformed threshold {raw!r}")
    raise InputError(f"malformed threshold {raw!r}")


class Evaluator:
    """Trust graph + quorum-set evaluator over one raw node list."""

    def __init__(self, nodes: Sequence[dict], dangling: str) -> None:
        if dangling not in ("strict", "alias0"):
            raise InputError(f"unknown dangling policy {dangling!r}")
        self.dangling = dangling
        self.ids: List[str] = []
        self.index: Dict[str, int] = {}
        for node in nodes:
            key = node.get("publicKey")
            if not isinstance(key, str) or not key:
                raise InputError("node without a publicKey")
            if key in self.index:
                raise InputError(f"duplicate publicKey {key!r}")
            self.index[key] = len(self.ids)
            self.ids.append(key)
        self.n = len(self.ids)
        self.qsets: List[Optional[dict]] = [
            self._resolve(node.get("quorumSet"), 0) for node in nodes
        ]
        self.succ: List[List[int]] = [
            self._edges(q) for q in self.qsets
        ]

    def _resolve(self, qset: object, depth: int) -> Optional[dict]:
        """Raw quorumSet → {t, members: [idx...], inner: [...]} with the
        dangling policy applied (strict: unknown refs dropped; alias0:
        aliased to vertex 0).  None ⇒ null qset (Q2, never satisfiable)."""
        if qset is None:
            return None
        if not isinstance(qset, dict):
            raise InputError(f"malformed quorumSet {type(qset).__name__}")
        if depth > MAX_DEPTH:
            raise InputError(f"quorumSet nesting exceeds depth {MAX_DEPTH}")
        if qset.get("threshold") is None and not qset.get("validators") \
                and not qset.get("innerQuorumSets"):
            return None  # empty/null qset
        members: List[int] = []
        for key in qset.get("validators") or []:
            v = self.index.get(key)
            if v is None:
                if self.dangling == "alias0":
                    members.append(0)
                continue  # strict: never-available ≡ dropped member
            members.append(v)
        inner = [
            self._resolve(iq, depth + 1)
            for iq in qset.get("innerQuorumSets") or []
        ]
        return {
            "t": _threshold(qset.get("threshold")),
            "members": members,
            "inner": inner,
        }

    def _edges(self, qset: Optional[dict]) -> List[int]:
        if qset is None:
            return []
        out = list(qset["members"])
        for iq in qset["inner"]:
            out.extend(self._edges(iq))
        return out

    # -- semantics ---------------------------------------------------------

    def slice_satisfied(self, owner: int, avail: Sequence[bool]) -> bool:
        if not avail[owner]:  # Q4: self-availability
            return False
        return self._qset_satisfied(self.qsets[owner], avail)

    def _qset_satisfied(self, qset: Optional[dict], avail: Sequence[bool]) -> bool:
        if qset is None:  # Q2
            return False
        t = qset["t"]
        m_count = len(qset["members"]) + len(qset["inner"])
        if t <= 0 or t > m_count:  # Q3 normalization
            return False
        met = sum(1 for v in qset["members"] if avail[v])
        for iq in qset["inner"]:
            if met >= t:
                return True
            if self._qset_satisfied(iq, avail):
                met += 1
        return met >= t

    def max_quorum(self, candidates: Sequence[int]) -> List[int]:
        """Greatest fixpoint of the candidate set: repeatedly drop members
        whose slice is unsatisfied until stable."""
        avail = [False] * self.n
        for v in candidates:
            avail[v] = True
        nodes = list(candidates)
        while True:
            kept = [v for v in nodes if self.slice_satisfied(v, avail)]
            if len(kept) == len(nodes):
                return kept
            for v in nodes:
                if v not in kept:
                    avail[v] = False
            nodes = kept

    def is_quorum(self, members: Sequence[int]) -> bool:
        unique = sorted(set(members))
        return bool(unique) and len(self.max_quorum(unique)) == len(unique)

    # -- SCC structure -----------------------------------------------------

    def tarjan(self) -> List[List[int]]:
        """Iterative Tarjan: list of SCCs (each a vertex list)."""
        UNSET = -1
        disc = [UNSET] * self.n
        low = [0] * self.n
        on_stack = [False] * self.n
        stack: List[int] = []
        comps: List[List[int]] = []
        timer = 0
        for root in range(self.n):
            if disc[root] != UNSET:
                continue
            work: List[Tuple[int, int]] = [(root, 0)]
            while work:
                v, pi = work[-1]
                if pi == 0:
                    disc[v] = low[v] = timer
                    timer += 1
                    stack.append(v)
                    on_stack[v] = True
                advanced = False
                for i in range(pi, len(self.succ[v])):
                    w = self.succ[v][i]
                    if disc[w] == UNSET:
                        work[-1] = (v, i + 1)
                        work.append((w, 0))
                        advanced = True
                        break
                    if on_stack[w]:
                        low[v] = min(low[v], disc[w])
                if advanced:
                    continue
                if low[v] == disc[v]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack[w] = False
                        comp.append(w)
                        if w == v:
                            break
                    comps.append(comp)
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[v])
        return comps

    def quorum_bearing_sccs(self) -> List[List[int]]:
        return [scc for scc in self.tarjan() if self.max_quorum(scc)]


# ---------------------------------------------------------------------------
# certificate validation


def _require(cond: bool, message: str) -> None:
    if not cond:
        raise CheckFailure(message)


def _check_witness_quorum(
    ev: Evaluator, label: str, ids: Sequence[str], evidence: Sequence[dict]
) -> Set[int]:
    _require(bool(ids), f"witness {label} is empty")
    indices: List[int] = []
    for pk in ids:
        v = ev.index.get(pk)
        _require(v is not None, f"witness {label} names unknown node {pk!r}")
        indices.append(v)  # type: ignore[arg-type]
    _require(
        len(set(indices)) == len(indices),
        f"witness {label} repeats a node",
    )
    _require(
        ev.is_quorum(indices),
        f"witness {label} is not a self-contained quorum under this "
        f"checker's evaluator",
    )
    # The certificate's own per-member evidence must agree with this
    # checker's evaluation — a cert claiming an unsatisfied member is
    # internally unsound even when the set happens to be a quorum.
    _require(
        all(isinstance(row, dict) for row in evidence),
        f"witness {label} evidence rows are not objects",
    )
    ev_ids = [row.get("id") for row in evidence]
    _require(
        all(isinstance(pk, str) for pk in ev_ids)
        and sorted(ev_ids) == sorted(ids),
        f"witness {label} evidence rows do not cover its members",
    )
    _require(
        all(row.get("satisfied") is True for row in evidence),
        f"witness {label} evidence marks a member unsatisfied",
    )
    return set(indices)


# Prune rules this checker knows how to re-verify; any other id is unsound
# by definition (a claim nothing independent can re-check).
PRUNE_RULES = ("empty-max-quorum",)


def _check_pruned_blocks(
    ev: Evaluator, entry: dict, sample: Optional[int]
) -> str:
    """Re-verify a sweep entry's pruned mass; returns a note ('' if none).

    Every pruned block is a standalone claim: "the maximal candidate of
    the 2^k windows sharing this high-bit prefix contains no quorum".
    This checker rebuilds that candidate from the entry's `enumeration`
    bit map and re-runs its OWN greatest fixpoint on it — sharing no code
    with the engine that pruned."""
    pruned = entry.get("windows_pruned_guard", 0)
    blocks = entry.get("pruned_blocks")
    if not pruned:
        _require(
            blocks is None,
            "pruned_blocks ledger present with zero pruned windows",
        )
        return ""
    _require(
        isinstance(blocks, dict),
        "nonzero windows_pruned_guard without a pruned_blocks ledger is "
        "unverifiable and therefore unsound",
    )
    size = entry["size"]
    bits = size - 1
    k = blocks.get("k")
    rule = blocks.get("rule")
    prefixes = blocks.get("prefixes")
    _require(
        rule in PRUNE_RULES,
        f"unknown prune rule {rule!r}: nothing independent can re-verify it",
    )
    _require(
        isinstance(k, int) and 0 <= k <= bits,
        f"pruned_blocks k={k!r} outside [0, {bits}]",
    )
    _require(
        isinstance(prefixes, list)
        and all(isinstance(p, int) and not isinstance(p, bool) for p in prefixes),
        "pruned_blocks prefixes must be a list of integers",
    )
    block_space = 1 << (bits - k)
    _require(
        all(0 <= p < block_space for p in prefixes),
        f"pruned block prefix outside [0, {block_space})",
    )
    _require(
        len(set(prefixes)) == len(prefixes),
        "pruned_blocks repeats a prefix (double-counted windows)",
    )
    _require(
        len(prefixes) * (1 << k) == pruned,
        f"windows_pruned_guard {pruned} != {len(prefixes)} blocks * 2^{k}",
    )
    # Disjointness with the checkpoint-resumed prefix: the sum invariant
    # only means "every window claimed exactly once" if no pruned block
    # dips below the resumed cut (the engine clips there; a forged cert
    # could otherwise double-claim resumed windows as pruned and shrink
    # windows_enumerated by the same amount with every block still
    # re-verifying).
    resumed = entry.get("windows_resumed_prefix", 0)
    if isinstance(resumed, int) and resumed > 0:
        _require(
            all((p << k) >= resumed for p in prefixes),
            "a pruned block overlaps the checkpoint-resumed prefix "
            "(windows claimed by two ledger terms at once)",
        )
    enum = entry.get("enumeration") or {}
    fixed = enum.get("fixed")
    bit_ids = enum.get("bit_nodes") or []
    _require(
        isinstance(fixed, str)
        and isinstance(bit_ids, list)
        and all(isinstance(b, str) for b in bit_ids),
        "pruned mass without a usable enumeration (fixed + bit_nodes) block",
    )
    _require(
        len(bit_ids) == bits,
        f"enumeration names {len(bit_ids)} bit nodes; expected {bits}",
    )
    scc_ids = set(entry.get("nodes") or [])
    _require(
        len(set(bit_ids)) == bits
        and fixed not in bit_ids
        and {fixed, *bit_ids} == scc_ids,
        "enumeration is not a permutation of the ledger SCC",
    )
    bit_ix: List[int] = []
    for pk in bit_ids:
        v = ev.index.get(pk)
        _require(v is not None, f"enumeration names unknown node {pk!r}")
        bit_ix.append(v)  # type: ignore[arg-type]
    checked = list(prefixes)
    if sample and 0 < sample < len(checked):
        stride = max(len(checked) // sample, 1)
        checked = checked[::stride][:sample]
    free = bit_ix[:k]
    for p in checked:
        members = free + [
            bit_ix[k + j] for j in range(bits - k) if (p >> j) & 1
        ]
        _require(
            not ev.max_quorum(members),
            f"pruned block {p} is unsound: its maximal candidate contains "
            f"a quorum under this checker's evaluator",
        )
    note = f"pruned blocks re-verified: {len(checked)}/{len(prefixes)}"
    if len(checked) < len(prefixes):
        note += " (sampled)"
    return note


def _check_ledger_entry(
    entry: dict,
    qb_ids: Set[str],
    scc_select: str,
    ev: Optional[Evaluator] = None,
    sample: Optional[int] = None,
) -> str:
    _require(isinstance(entry, dict), "coverage ledger entry is not an object")
    size = entry.get("size")
    nodes = entry.get("nodes") or []
    _require(isinstance(size, int) and size >= 1, "ledger entry without a size")
    _require(
        len(nodes) == size and len(set(nodes)) == size,
        "ledger entry node list does not match its size",
    )
    if scc_select == "quorum-bearing":
        _require(
            set(nodes) == qb_ids,
            "ledger SCC is not the quorum-bearing SCC this checker found",
        )
    backend = str(entry.get("backend", "?"))
    if "window_space" in entry:
        space = entry["window_space"]
        _require(
            space == 1 << (size - 1),
            f"window_space {space} != 2^(size-1) = {1 << (size - 1)}",
        )
        parts = {
            key: entry.get(key)
            for key in ("windows_enumerated", "windows_pruned_guard",
                        "windows_skipped_pack_fill", "windows_cancelled")
        }
        for key, val in parts.items():
            _require(
                isinstance(val, int) and val >= 0,
                f"ledger field {key} missing or negative",
            )
        # Optional term: a checkpoint-resumed sweep did not re-drain the
        # fingerprint-matched prefix an earlier run already covered — the
        # prefix counts toward the space without inflating the run's own
        # enumerated count (docs/PARITY.md §Certificate invariants).
        resumed = entry.get("windows_resumed_prefix", 0)
        _require(
            isinstance(resumed, int) and resumed >= 0,
            "ledger field windows_resumed_prefix malformed or negative",
        )
        total = sum(parts.values()) + resumed  # type: ignore[arg-type]
        _require(
            total == space,
            f"ledger arithmetic: enumerated+pruned+skipped+cancelled"
            f"+resumed = {total} != window space {space}",
        )
        _require(
            parts["windows_cancelled"] == 0,
            "a true verdict cannot rest on cancelled windows",
        )
        _require(
            parts["windows_skipped_pack_fill"] == 0,
            "a true verdict cannot rest on pack-skipped windows",
        )
        # Pruned mass (ISSUE 10): formerly a reserved always-zero term, now
        # verifiable — every pruned block must be re-provable from the raw
        # JSON by this checker's own fixpoint evaluator (module docs).
        prune_note = ""
        if ev is not None:
            prune_note = _check_pruned_blocks(ev, entry, sample)
        note = f"sweep ledger: {parts['windows_enumerated']}/{space} windows"
        if parts["windows_pruned_guard"]:
            note += f" (+{parts['windows_pruned_guard']} guard-pruned)"
        if resumed:
            note += f" (+{resumed} checkpoint-resumed)"
        if prune_note:
            note += f"; {prune_note}"
        return note
    if backend in ("cpp", "python"):
        _require(
            isinstance(entry.get("bnb_calls"), int) and entry["bnb_calls"] >= 1,
            "oracle ledger entry without a positive bnb_calls count",
        )
        return f"oracle ledger: {entry['bnb_calls']} B&B calls"
    if backend == "tpu-frontier":
        chunks = entry.get("frontier_chunks_drained")
        _require(
            isinstance(chunks, int) and chunks >= 1,
            "frontier ledger entry without a positive chunk count",
        )
        return f"frontier ledger: {chunks} chunks drained"
    raise CheckFailure(f"ledger entry with unknown backend {backend!r}")


def _check_relaxed_certificate(
    cert: dict, nodes: Sequence[dict], query: dict
) -> List[str]:
    """Validate a relaxed two-family certificate (qi-query, ISSUE 12).

    The second family rides inside the certificate (``query.family_b``),
    so the claim is self-contained: this checker re-resolves BOTH
    families with its own evaluator, re-proves the family-A guard count,
    and for a ``false`` verdict re-proves the cross-family witness —
    ``q1`` a family-A quorum, ``q2`` a family-B quorum, disjoint, every
    member's slice evidence agreeing with this checker's own evaluation.
    A ``true`` verdict's ledger must claim FULL coverage of the
    ``2^m - 1`` nonempty windows of each family-A quorum-bearing SCC
    (docs/PARITY.md §Two-family invariants)."""
    notes: List[str] = []
    verdict = cert.get("verdict")
    _require(isinstance(verdict, bool), "certificate without a boolean verdict")
    dangling = str(cert.get("dangling", "strict"))
    fam_b = query.get("family_b")
    _require(
        isinstance(fam_b, list) and bool(fam_b),
        "relaxed certificate without an embedded family_b node array",
    )
    ev_a = Evaluator(nodes, dangling)
    ev_b = Evaluator(fam_b, dangling)  # type: ignore[arg-type]
    _require(
        ev_a.ids == ev_b.ids,
        "relaxed families do not share one node set in one order",
    )
    qb_a = ev_a.quorum_bearing_sccs()
    guard = cert.get("guard") or {}
    _require(
        guard.get("quorum_bearing_sccs") == len(qb_a),
        f"relaxed guard claims {guard.get('quorum_bearing_sccs')} "
        f"family-A quorum-bearing SCC(s); this checker found {len(qb_a)}",
    )
    notes.append(
        f"relaxed guard: {len(qb_a)} family-A quorum-bearing SCC(s) "
        f"confirmed"
    )
    if not verdict:
        witness = cert.get("witness") or {}
        evidence = witness.get("evidence") or {}
        s1 = _check_witness_quorum(ev_a, "q1", witness.get("q1") or [],
                                   evidence.get("q1") or [])
        s2 = _check_witness_quorum(ev_b, "q2", witness.get("q2") or [],
                                   evidence.get("q2") or [])
        _require(not (s1 & s2), "cross-family witness quorums intersect")
        notes.append(
            f"cross-family witness confirmed: disjoint A-quorum "
            f"({len(s1)}) and B-quorum ({len(s2)})"
        )
        return notes
    vacuous = cert.get("vacuous")
    if vacuous == "no_quorum_family_a":
        _require(len(qb_a) == 0,
                 "vacuous no_quorum_family_a but family A bears a quorum")
        notes.append("vacuous true confirmed: family A holds no quorum")
        return notes
    if vacuous == "no_quorum_family_b":
        _require(
            not ev_b.max_quorum(list(range(ev_b.n))),
            "vacuous no_quorum_family_b but family B's graph-wide "
            "fixpoint is nonempty",
        )
        notes.append("vacuous true confirmed: family B holds no quorum")
        return notes
    entries = (cert.get("coverage") or {}).get("sccs") or []
    _require(bool(entries), "relaxed true verdict without a coverage ledger")
    scc_sets = [frozenset(ev_a.ids[v] for v in scc) for scc in qb_a]
    for entry in entries:
        size = entry.get("size")
        space = entry.get("window_space")
        enumerated = entry.get("windows_enumerated")
        _require(isinstance(size, int) and size > 0,
                 "relaxed ledger entry without a positive SCC size")
        _require(
            space == (1 << size) - 1,
            f"relaxed window space {space} != 2^{size} - 1",
        )
        _require(
            enumerated == space,
            f"relaxed coverage incomplete: {enumerated} of {space} "
            f"windows enumerated",
        )
        entry_nodes = frozenset(entry.get("nodes") or [])
        _require(
            entry_nodes in scc_sets,
            "relaxed ledger entry's nodes are not a family-A "
            "quorum-bearing SCC",
        )
        notes.append(
            f"relaxed coverage: {enumerated}/{space} windows over a "
            f"{size}-node SCC"
        )
    _require(
        len(entries) == len(qb_a),
        f"relaxed ledger covers {len(entries)} SCC(s); family A bears "
        f"{len(qb_a)}",
    )
    return notes


def _check_query_result_certificate(
    cert: dict, nodes: Sequence[dict], sample: Optional[int]
) -> List[str]:
    """Validate a ``qi-query-cert/1`` analytics result certificate.

    Splitting/blocking results carry a re-provable proof block — a full
    ``qi-cert/1`` for the reduced/masked network plus the exact node
    list it is against — which re-validates through this checker's
    normal witness-evidence / no-quorum paths.  A blocking proof's
    masked node list is additionally RE-DERIVED from the primary
    snapshot (masking is pure quorumSet nulling), so a forged embedded
    list cannot smuggle a different network past the re-proof."""
    notes: List[str] = []
    query = cert.get("query") or {}
    _require(query.get("kind") == "analytics",
             f"unknown query-cert kind {query.get('kind')!r}")
    digest = cert.get("result_digest")
    _require(isinstance(digest, str) and len(digest) == 32,
             "query certificate without a result digest")
    metric = query.get("metric")
    notes.append(f"analytics result cert ({metric}) digest present")
    proof = cert.get("proof")
    if proof is None:
        return notes
    _require(
        isinstance(proof, dict) and isinstance(proof.get("cert"), dict)
        and isinstance(proof.get("nodes"), list),
        "analytics proof block without cert + nodes",
    )
    claim = proof.get("claim")
    proof_nodes = proof["nodes"]
    result = cert.get("result") or {}
    if claim == "blocking-halts":
        blocking = result.get("blocking")
        _require(isinstance(blocking, list) and bool(blocking),
                 "blocking proof without the claimed blocking set")
        gone = set(blocking)
        rederived = [
            {**node, "quorumSet": None}
            if node.get("publicKey") in gone else dict(node)
            for node in nodes
        ]
        _require(
            _canon_nodes(rederived) == _canon_nodes(proof_nodes),
            "blocking proof nodes differ from masking the primary "
            "snapshot with the claimed blocking set",
        )
        _require(
            proof["cert"].get("verdict") is False
            and proof["cert"].get("no_quorum") is True,
            "blocking proof cert does not claim a halted network "
            "(false + no_quorum)",
        )
    elif claim == "splitting-witness":
        splitting = result.get("splitting")
        _require(isinstance(splitting, list) and bool(splitting),
                 "splitting proof without the claimed splitting set")
        primary_ids = {n.get("publicKey") for n in nodes}
        _require(
            all(k in primary_ids for k in splitting),
            "splitting set names nodes outside the primary snapshot",
        )
        rederived = _byzantine_delete(nodes, splitting)
        _require(
            _canon_nodes(rederived) == _canon_nodes(proof_nodes),
            "splitting proof nodes differ from this checker's own "
            "byzantine deletion of the claimed set from the primary "
            "snapshot",
        )
        _require(
            proof["cert"].get("verdict") is False
            and isinstance(proof["cert"].get("witness"), dict),
            "splitting proof cert does not witness a disjoint pair",
        )
    else:
        raise CheckFailure(f"unknown analytics proof claim {claim!r}")
    notes.extend(check_certificate(proof["cert"], proof_nodes, sample=sample))
    notes.append(f"analytics proof re-proved ({claim})")
    return notes


def _canon_nodes(nodes: Sequence[dict]) -> str:
    return json.dumps(list(nodes), sort_keys=True, separators=(",", ":"),
                      default=str)


def _scrub_qset(qset: object, removed: frozenset) -> Tuple[object, bool]:
    """Byzantine ``delete`` on one raw quorum set: ``(qset', trivial)``.

    This checker's OWN implementation of the FBAS delete semantics
    (threshold decremented per deleted member — byzantine nodes vote for
    everyone; a set driven to threshold <= 0 becomes trivially
    satisfiable and folds into its parent), deliberately sharing no code
    with ``analytics/splitting.py``: the splitting proof's reduced
    network is re-derived HERE, so a forged embedded node list cannot
    smuggle a different network past the re-proof.  Degenerate
    thresholds (<= 0 to begin with, non-numeric) are left untouched,
    mirroring the engine's pinned Q3 handling."""
    if not isinstance(qset, dict):
        return qset, False
    t = qset.get("threshold")
    if isinstance(t, str):
        try:
            t = int(t)
        except ValueError:
            return qset, False
    if not isinstance(t, int) or isinstance(t, bool):
        return qset, False
    if t <= 0:
        return qset, False
    validators = [
        v for v in (qset.get("validators") or []) if v not in removed
    ]
    t -= len(qset.get("validators") or []) - len(validators)
    inner: List[dict] = []
    for child in qset.get("innerQuorumSets") or []:
        scrubbed, trivial = _scrub_qset(child, removed)
        if trivial:
            t -= 1  # the child now votes unconditionally
        else:
            inner.append(scrubbed)  # type: ignore[arg-type]
    if t <= 0:
        return None, True
    return {"threshold": t, "validators": validators,
            "innerQuorumSets": inner}, False


def _byzantine_delete(
    nodes: Sequence[dict], removed_keys: Sequence[str]
) -> List[dict]:
    """The FBAS ``delete`` operation over a raw node list (see
    :func:`_scrub_qset`) — the checker's independent twin of the
    analytics engine's reduction."""
    removed = frozenset(removed_keys)
    out: List[dict] = []
    for node in nodes:
        key = node.get("publicKey")
        if key in removed:
            continue
        q = node.get("quorumSet")
        if q is None:
            out.append(dict(node))
            continue
        scrubbed, trivial = _scrub_qset(q, removed)
        if trivial:
            scrubbed = {"threshold": 1, "validators": [key],
                        "innerQuorumSets": []}
        out.append({**node, "quorumSet": scrubbed})
    return out


def check_certificate(
    cert: dict, nodes: Sequence[dict], sample: Optional[int] = None
) -> List[str]:
    """Validate ``cert`` against the raw node list; returns human-readable
    notes, raises :class:`CheckFailure` on the first unsound claim.
    ``sample``: re-verify at most that many pruned blocks per ledger entry
    (deterministic stride); None/0 re-verifies every block.

    Since qi-query (ISSUE 12) two further shapes validate here: a
    ``qi-cert/1`` carrying a ``query`` block with ``kind: relaxed`` (the
    two-family certificate — family B rides inside it) and the
    ``qi-query-cert/1`` analytics result certificate (re-provable
    splitting/blocking proofs)."""
    if cert.get("schema") == "qi-query-cert/1":
        return _check_query_result_certificate(cert, nodes, sample)
    notes: List[str] = []
    _require(cert.get("schema") == "qi-cert/1",
             f"unknown certificate schema {cert.get('schema')!r}")
    query = cert.get("query")
    if isinstance(query, dict) and query.get("kind") == "relaxed":
        return _check_relaxed_certificate(cert, nodes, query)
    verdict = cert.get("verdict")
    _require(isinstance(verdict, bool), "certificate without a boolean verdict")
    dangling = str(cert.get("dangling", "strict"))
    scc_select = str(cert.get("scc_select", "quorum-bearing"))
    ev = Evaluator(nodes, dangling)
    graph_claim = cert.get("graph") or {}
    if "n" in graph_claim:
        _require(graph_claim["n"] == ev.n,
                 f"certificate graph.n {graph_claim['n']} != {ev.n} nodes")
    qb = ev.quorum_bearing_sccs()
    guard = cert.get("guard") or {}
    _require(
        guard.get("quorum_bearing_sccs") == len(qb),
        f"guard claims {guard.get('quorum_bearing_sccs')} quorum-bearing "
        f"SCC(s); this checker found {len(qb)}",
    )
    notes.append(f"guard: {len(qb)} quorum-bearing SCC(s) confirmed")

    if verdict:
        _require(len(qb) == 1,
                 "true verdict with != 1 quorum-bearing SCC is vacuous")
        entries = (cert.get("coverage") or {}).get("sccs") or []
        _require(bool(entries), "true verdict without a coverage ledger")
        qb_ids = {ev.ids[v] for v in qb[0]}
        for entry in entries:
            notes.append(
                _check_ledger_entry(entry, qb_ids, scc_select, ev=ev,
                                    sample=sample)
            )
        return notes

    witness = cert.get("witness")
    if witness is None:
        _require(
            cert.get("no_quorum") is True,
            "false verdict without a witness must claim no_quorum",
        )
        _require(
            not ev.max_quorum(list(range(ev.n))),
            "no_quorum claimed but the graph-wide greatest fixpoint is "
            "nonempty",
        )
        notes.append("no-quorum claim confirmed (graph-wide fixpoint empty)")
        return notes
    evidence = witness.get("evidence") or {}
    s1 = _check_witness_quorum(ev, "q1", witness.get("q1") or [],
                               evidence.get("q1") or [])
    s2 = _check_witness_quorum(ev, "q2", witness.get("q2") or [],
                               evidence.get("q2") or [])
    _require(not (s1 & s2), "witness quorums intersect")
    notes.append(
        f"witness confirmed: disjoint quorums of size {len(s1)} and {len(s2)}"
    )
    return notes


# ---------------------------------------------------------------------------


def _load_nodes(path: str) -> List[dict]:
    try:
        with open(path, encoding="utf-8") as fh:
            raw = json.load(fh)
    except (OSError, json.JSONDecodeError) as exc:
        raise InputError(f"cannot read FBAS JSON {path}: {exc}")
    if isinstance(raw, dict) and isinstance(raw.get("nodes"), list):
        raw = raw["nodes"]
    if not isinstance(raw, list):
        raise InputError(f"{path}: expected a stellarbeat node list")
    return raw


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("cert", help="qi-cert/1 certificate JSON")
    parser.add_argument("fbas", help="raw stellarbeat JSON the verdict ran on")
    parser.add_argument("-q", "--quiet", action="store_true",
                        help="suppress the per-check notes")
    parser.add_argument("--sample", type=int, default=None, metavar="N",
                        help="re-verify at most N pruned blocks per sweep "
                             "ledger entry (deterministic stride) instead "
                             "of all of them — for huge pruned ledgers")
    args = parser.parse_args(argv)
    try:
        try:
            with open(args.cert, encoding="utf-8") as fh:
                cert = json.load(fh)
        except (OSError, json.JSONDecodeError) as exc:
            raise InputError(f"cannot read certificate {args.cert}: {exc}")
        if not isinstance(cert, dict):
            raise InputError(f"{args.cert}: certificate is not a JSON object")
        notes = check_certificate(cert, _load_nodes(args.fbas),
                                  sample=args.sample)
    except CheckFailure as exc:
        print(f"UNSOUND: {exc}", file=sys.stderr)
        return 1
    except InputError as exc:
        print(f"input error: {exc}", file=sys.stderr)
        return 2
    except Exception as exc:  # adversarial inputs must never traceback:
        # a certificate hostile enough to break the checker's structural
        # assumptions is ill-formed input, and the documented contract is
        # exit 2 — not an uncaught TypeError that a CI consumer would
        # misread as "unsound certificate".
        print(
            f"input error: structurally ill-formed certificate "
            f"({type(exc).__name__}: {exc})",
            file=sys.stderr,
        )
        return 2
    if not args.quiet:
        print(f"certificate OK ({args.cert}, verdict={cert['verdict']})")
        for note in notes:
            print(f"  {note}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
