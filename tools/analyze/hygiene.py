"""qi-hygiene: device-interaction discipline on the hot paths (pass 7).

The search is NP-hard, so every accidental host↔device sync or silent
recompile inside the window-enumeration and serve-drain loops multiplies
across ``2^n`` candidates and millions of requests.  This pass builds a
**hot-region map** — every function reachable from the sweep drive/pack
drain loops, the serve drain (fused and unfused), ``BatchFormer._flush``
and the frontier worklist, seeded from the telemetry span inventory in
``surface_inventory.json`` — over the shared call graph
(:mod:`tools.analyze.callgraph`), then checks three rules inside it:

- ``hygiene-host-sync`` — ``.item()``/``.tolist()``/``float()``/
  ``bool()``/``int()``/``np.asarray``/``device_get``/
  ``block_until_ready`` applied to a **device value**, taint-tracked
  from jit/pallas dispatch results (the way ``jax-tracer-leak`` tracks
  tracers).  Each one is a device round-trip that stalls the pipeline.
- ``hygiene-recompile-hazard`` — a jit entry invoked with argument
  arrays built outside the canonical pad ladder
  (``encode/circuit.py``: ``ladder_up``/``pad_targets``/…), with
  weak-shape positionals (string/dict/list literals retrace per value
  or per structure), or a ``jax.jit`` constructed inside a hot loop
  (a fresh jit object re-traces every call).
- ``hygiene-transfer-in-loop`` — ``device_put``/``jnp.asarray``
  materialization inside a hot loop whose operand is loop-invariant
  and should hoist.

Taint is deliberately shallow — direct assignment chains only, no
container flow — so a finding is worth reading; every finding carries
its **hot-path witness** (the span-seeded call chain that makes the
function hot).  Suppress a reviewed sanctioned site with
``# qi-lint: allow(rule) — reason`` like every other rule.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from tools.analyze.callgraph import (
    FnInfo,
    FnKey,
    PackageGraph,
    build_graph,
    reachable,
    ref_of,
)
from tools.analyze.lint import FileContext, Finding

PACKAGE = "quorum_intersection_tpu"

# Hot-loop telemetry spans (must exist in the span inventory): the sweep
# drive/pack drain loops, the serve drain + solve stages, the pipeline
# many-SCC loop.
HOT_SPAN_SEEDS = (
    "pipeline.check_many",
    "serve.batch",
    "serve.solve",
    "sweep.drive",
    "sweep.pack",
)

# Hot functions without their own span: the fuse flush (runs inside the
# serve drain's fuse window) and the frontier worklist.
HOT_FUNCTION_SEEDS = (
    ("quorum_intersection_tpu/fuse.py", "BatchFormer._flush"),
    ("quorum_intersection_tpu/backends/tpu/frontier.py",
     "TpuFrontierBackend.check_scc"),
)

INVENTORY = "tools/analyze/surface_inventory.json"

# The canonical pad ladder surface in encode/circuit.py: an argument
# whose shape went through any of these is compile-cache-friendly.
LADDER_NAMES = frozenset({
    "ladder_up", "pad_targets", "pad_circuit", "pack_circuits",
    "plan_packs", "PAD_LADDER", "LANE_TILE",
})

_JIT_NAMES = frozenset({"jit", "pallas_call"})
_ARRAY_CTORS = frozenset({"asarray", "array", "zeros", "ones", "full"})
_ARRAY_MODULES = frozenset({"np", "numpy", "jnp"})
_DEVICE_MODULES = frozenset({"jnp"})
_SYNC_METHODS = frozenset({"item", "tolist"})
_SYNC_CASTS = frozenset({"int", "float", "bool"})


def default_targets(root: Path) -> List[str]:
    """Every package module, sorted for deterministic output."""
    pkg = root / PACKAGE
    return sorted(
        str(p.relative_to(root)) for p in pkg.rglob("*.py")
    )


def _names_in(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _mentions(node: ast.AST, names: FrozenSet[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in names:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in names:
            return True
    return False


def _callee_name(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _is_jit_ctor(call: ast.Call) -> bool:
    """``jax.jit(...)`` / ``jit(...)`` / ``pl.pallas_call(...)``."""
    return _callee_name(call) in _JIT_NAMES


def _direct_jit_fns(graph: PackageGraph) -> Set[FnKey]:
    """Functions whose own body mentions jit/pallas_call (dispatch factories)."""
    return {
        key for key, fn in graph.infos.items()
        if _mentions(fn.node, _JIT_NAMES)
    }


def _laddered_fns(graph: PackageGraph) -> Set[FnKey]:
    """Functions routing through the pad ladder, transitively over calls."""
    out = {
        key for key, fn in graph.infos.items()
        if _mentions(fn.node, LADDER_NAMES)
    }
    changed = True
    while changed:
        changed = False
        for key, fn in graph.infos.items():
            if key in out:
                continue
            for call_ref, _line in fn.calls:
                callee = graph.resolve(call_ref)
                if callee is not None and callee in out:
                    out.add(key)
                    changed = True
                    break
    return out


def _load_span_inventory(root: Path,
                         inventory_path: Optional[Path]) -> Optional[Set[str]]:
    path = inventory_path if inventory_path is not None else root / INVENTORY
    try:
        data = json.loads(Path(path).read_text(encoding="utf-8"))
        spans = data["telemetry"]["span"]
    except (OSError, ValueError, KeyError, TypeError):
        return None
    return {str(s) for s in spans}


class _HygieneScanner:
    """One hot function: shallow device taint + the three rules."""

    def __init__(self, graph: PackageGraph, fn: FnInfo, ctx: FileContext,
                 witness: str, jit_fns: Set[FnKey], ladder_fns: Set[FnKey],
                 instances: Dict[str, str]) -> None:
        self.graph = graph
        self.fn = fn
        self.ctx = ctx
        self.witness = witness
        self.jit_fns = jit_fns
        self.ladder_fns = ladder_fns
        self.instances = instances
        self.dispatchers: Set[str] = set()
        self.tainted: Set[str] = set()
        self.laddered: Set[str] = set()
        self.loop_assigned: List[Set[str]] = []
        self.findings: List[Tuple[str, int, str]] = []

    # -- expression classification ------------------------------------------

    def _resolve_call(self, call: ast.Call) -> Optional[FnKey]:
        cls = self.fn.cls_name
        call_ref = ref_of(call.func, self.fn.key[0], cls, self.instances)
        if call_ref is None:
            return None
        return self.graph.resolve(call_ref)

    def _kinds(self, expr: ast.AST) -> Set[str]:
        """``{"device", "dispatcher"}`` membership of an expression."""
        if isinstance(expr, ast.Name):
            out: Set[str] = set()
            if expr.id in self.tainted:
                out.add("device")
            if expr.id in self.dispatchers:
                out.add("dispatcher")
            return out
        if isinstance(expr, (ast.Attribute, ast.Subscript, ast.Starred)):
            return self._kinds(expr.value) & {"device"}
        if isinstance(expr, ast.IfExp):
            return self._kinds(expr.body) | self._kinds(expr.orelse)
        if isinstance(expr, ast.Call):
            name = _callee_name(expr)
            if _is_jit_ctor(expr):
                return {"dispatcher"}
            if name == "device_put":
                return {"device"}
            if name is not None and name in self.dispatchers:
                # calling a dispatch entry yields a device value; factory
                # chains (a factory returning a factory) stay dispatchers
                return {"device", "dispatcher"}
            callee = self._resolve_call(expr)
            if callee is not None and callee in self.jit_fns:
                return {"dispatcher"}
            return set()
        return set()

    def _expr_laddered(self, expr: ast.AST) -> bool:
        if _mentions(expr, LADDER_NAMES):
            return True
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Name) and sub.id in self.laddered:
                return True
            if isinstance(sub, ast.Call):
                callee = self._resolve_call(sub)
                if callee is not None and callee in self.ladder_fns:
                    return True
        return False

    # -- findings -----------------------------------------------------------

    def _flag(self, rule: str, line: int, message: str) -> None:
        self.findings.append(
            (rule, line,
             f"{message} [hot via {self.witness}]"))

    # -- sinks / hazards ----------------------------------------------------

    def _check_call(self, call: ast.Call, loop_depth: int) -> None:
        f = call.func
        name = _callee_name(call)
        in_loop = loop_depth > 0
        # host-sync sinks -------------------------------------------------
        if isinstance(f, ast.Attribute):
            if f.attr in _SYNC_METHODS and "device" in self._kinds(f.value):
                self._flag(
                    "hygiene-host-sync", call.lineno,
                    f".{f.attr}() on a device value blocks on the device "
                    f"and round-trips to host in a hot region — keep the "
                    f"value on device or batch the readback")
            elif f.attr == "block_until_ready":
                self._flag(
                    "hygiene-host-sync", call.lineno,
                    "block_until_ready() stalls the dispatch pipeline in "
                    "a hot region — only sanctioned at explicit "
                    "measurement/drain barriers")
            elif f.attr in _ARRAY_CTORS and isinstance(f.value, ast.Name) \
                    and f.value.id in ("np", "numpy") \
                    and any("device" in self._kinds(a) for a in call.args):
                self._flag(
                    "hygiene-host-sync", call.lineno,
                    f"np.{f.attr}() on a device value forces a synchronous "
                    f"device→host transfer in a hot region")
        if name == "device_get":
            self._flag(
                "hygiene-host-sync", call.lineno,
                "device_get() is a synchronous device→host transfer in a "
                "hot region")
        elif name in _SYNC_CASTS and isinstance(f, ast.Name) \
                and len(call.args) == 1 \
                and "device" in self._kinds(call.args[0]):
            self._flag(
                "hygiene-host-sync", call.lineno,
                f"{name}() on a device value blocks until the device "
                f"result is ready — a hidden sync point in a hot region")
        # recompile hazards ----------------------------------------------
        if _is_jit_ctor(call) and in_loop:
            self._flag(
                "hygiene-recompile-hazard", call.lineno,
                "jit constructed inside a hot loop: a fresh jit object "
                "re-traces on every call — hoist it to module scope or a "
                "cached factory")
        if name is not None and name in self.dispatchers:
            for arg in call.args:
                if isinstance(arg, (ast.Dict, ast.List, ast.Set)) or (
                        isinstance(arg, ast.Constant)
                        and isinstance(arg.value, str)):
                    self._flag(
                        "hygiene-recompile-hazard", call.lineno,
                        "weak-shape positional argument (str/dict/list "
                        "literal) to a jit entry retraces per value or "
                        "per structure — pass arrays, or bind statics in "
                        "the factory")
                elif isinstance(arg, ast.Call) \
                        and _callee_name(arg) in _ARRAY_CTORS \
                        and not self._expr_laddered(arg):
                    self._flag(
                        "hygiene-recompile-hazard", call.lineno,
                        "jit-entry argument built outside the canonical "
                        "pad ladder: every distinct shape compiles a new "
                        "program — route the size through "
                        "encode/circuit.py ladder_up/pad_targets")
        # transfer-in-loop -----------------------------------------------
        if in_loop:
            is_put = name == "device_put"
            is_jnp_ctor = isinstance(f, ast.Attribute) \
                and f.value and isinstance(f.value, ast.Name) \
                and f.value.id in _DEVICE_MODULES and f.attr in _ARRAY_CTORS
            if is_put or is_jnp_ctor:
                loop_vars: Set[str] = set()
                for assigned in self.loop_assigned:
                    loop_vars |= assigned
                arg_names: Set[str] = set()
                for arg in call.args:
                    arg_names |= _names_in(arg)
                if call.args and not (arg_names & loop_vars):
                    what = "device_put" if is_put else f"jnp.{f.attr}"
                    self._flag(
                        "hygiene-transfer-in-loop", call.lineno,
                        f"{what}() of a loop-invariant operand inside a "
                        f"hot loop re-uploads the same data every "
                        f"iteration — hoist it above the loop")

    # -- taint bookkeeping --------------------------------------------------

    def _assign(self, node: ast.Assign) -> None:
        kinds = self._kinds(node.value)
        lad = self._expr_laddered(node.value)
        targets: List[ast.expr] = []
        for tgt in node.targets:
            if isinstance(tgt, ast.Tuple):
                targets.extend(tgt.elts)
            else:
                targets.append(tgt)
        for tgt in targets:
            if not isinstance(tgt, ast.Name):
                continue
            for kind, pool in (("device", self.tainted),
                               ("dispatcher", self.dispatchers)):
                if kind in kinds:
                    pool.add(tgt.id)
                else:
                    pool.discard(tgt.id)
            if lad:
                self.laddered.add(tgt.id)
            else:
                self.laddered.discard(tgt.id)

    def _collect_assigned(self, body: Sequence[ast.stmt]) -> Set[str]:
        out: Set[str] = set()
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Name) \
                        and isinstance(node.ctx, ast.Store):
                    out.add(node.id)
        return out

    # -- walking ------------------------------------------------------------

    def scan(self) -> None:
        for stmt in getattr(self.fn.node, "body", []):
            self._visit(stmt, 0)

    def _visit(self, node: ast.AST, loop_depth: int) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not self.fn.node:
            return  # nested defs are modeled as their own functions
        if isinstance(node, (ast.For, ast.While)):
            self.loop_assigned.append(self._collect_assigned(node.body))
            if isinstance(node, ast.For):
                self.loop_assigned[-1] |= _names_in(node.target)
                self._visit(node.iter, loop_depth)
            else:
                self._visit(node.test, loop_depth + 1)
            for child in node.body + node.orelse:
                self._visit(child, loop_depth + 1)
            self.loop_assigned.pop()
            return
        if isinstance(node, ast.Call):
            self._check_call(node, loop_depth)
        if isinstance(node, ast.Assign):
            self._visit(node.value, loop_depth)
            self._assign(node)
            return
        for child in ast.iter_child_nodes(node):
            self._visit(child, loop_depth)


def hot_region(graph: PackageGraph, span_inventory: Optional[Set[str]],
               ) -> Dict[FnKey, Tuple[str, Tuple[str, ...]]]:
    """Seed-and-close the hot-region map with witness chains."""
    seeds: Dict[FnKey, str] = {}
    for span in HOT_SPAN_SEEDS:
        if span_inventory is not None and span not in span_inventory:
            continue
        for key in graph.span_owners(span):
            seeds.setdefault(key, f"span {span}")
    for rel, qual in HOT_FUNCTION_SEEDS:
        if (rel, qual) in graph.infos:
            seeds.setdefault((rel, qual), f"fn {qual}")
    return reachable(graph, seeds)


def run_hygiene(root: Path, targets: Optional[Sequence[str]] = None,
                inventory_path: Optional[Path] = None,
                ) -> Tuple[List[Finding], List[str]]:
    """``(findings, notes)`` — the device-interaction hygiene pass."""
    rels = list(targets) if targets is not None else default_targets(root)
    graph = build_graph(root, rels)
    spans = _load_span_inventory(root, inventory_path)
    hot = hot_region(graph, spans)
    jit_fns = _direct_jit_fns(graph)
    ladder_fns = _laddered_fns(graph)
    findings: List[Finding] = []
    per_rule: Dict[str, int] = {}
    for key in sorted(hot):
        fn = graph.infos[key]
        label, chain = hot[key]
        witness = f"{label}: " + " -> ".join(chain)
        ctx = graph.ctxs[key[0]]
        cls_info = graph.classes.get((key[0], fn.cls_name or ""))
        instances = getattr(cls_info, "instances", {}) if cls_info else {}
        scanner = _HygieneScanner(
            graph, fn, ctx, witness, jit_fns, ladder_fns, instances)
        scanner.scan()
        seen: Set[Tuple[str, int]] = set()
        for rule, line, message in scanner.findings:
            if (rule, line) in seen or ctx.suppressed(rule, line):
                continue
            seen.add((rule, line))
            per_rule[rule] = per_rule.get(rule, 0) + 1
            findings.append(
                Finding(rule=rule, path=key[0], line=line, message=message))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    notes = [
        f"hygiene: {len(hot)} hot function(s) from span+fn seeds over "
        f"{len(graph.infos)} analyzed; "
        f"{per_rule.get('hygiene-host-sync', 0)} host-sync, "
        f"{per_rule.get('hygiene-recompile-hazard', 0)} recompile-hazard, "
        f"{per_rule.get('hygiene-transfer-in-loop', 0)} transfer-in-loop"
    ]
    return findings, notes
