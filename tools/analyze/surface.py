"""qi-surface: whole-program contract extraction + registry drift gates.

PRs 8-12 grew five hand-maintained contract registries — telemetry names
(docs/OBSERVABILITY.md), fault points (utils/faults.py + docs/ROBUSTNESS.md),
env knobs (utils/env.py), forced schedules (tools/analyze/schedules.py), and
the JSONL wire/cert field sets — whose agreement with the code was enforced
only by reviewer discipline.  This pass is the machine that holds them:

1. **Extraction**: walk the package AST and collect every *emitted*
   telemetry name (``counter``/``gauge``/``event``/``span`` call sites on
   the run record), every *fired* fault point (``fault_point("...")``),
   every ``qi_env*("QI_...")`` read, every forced-schedule name, and the
   JSONL wire fields (:mod:`tools.analyze.wire`).  Names must be string
   literals, module-level string constants, or dotted-prefix f-strings
   (recorded as ``prefix.*`` wildcards) — the ``telemetry-name-literal``
   lint rule keeps that sound.
2. **Inventory**: the extraction is serialized as a deterministic
   ``qi-surface/1`` JSON (:data:`INVENTORY_PATH`, committed).  A diff
   between the committed file and a fresh extraction is a finding
   (``surface-inventory-stale``) — regenerate with
   ``python -m tools.analyze surface --update-inventory`` and review the
   diff like any other contract change (this is also the wire pass's
   field-stability gate: a renamed journal/protocol field shows up here
   even when producer ⊇ consumer still holds).
3. **Drift gates**, both directions:

   - code emits a telemetry name the docs/OBSERVABILITY.md registry does
     not list (``surface-telemetry-unregistered``);
   - the registry lists a name the code never emits
     (``surface-registry-stale``);
   - a fault point is declared in utils/faults.py but no code path can
     fire it (``surface-fault-unfired``), or fired but undeclared
     (``surface-fault-undeclared``), or the docs/ROBUSTNESS.md fault
     table disagrees with the catalog in either direction
     (``surface-fault-undocumented`` / ``surface-fault-doc-stale``);
   - an env knob is declared in utils/env.py but never read
     (``surface-env-unread``), read but undeclared
     (``surface-env-undeclared``), or listed in a docs knob table
     without a declaration (``surface-env-doc-stale``).

The OBSERVABILITY/ROBUSTNESS registry *tables* are parsed as the source of
truth — their format is frozen (each doc says so): one row per line,
backticked names in the first cell, multiple names per row separated by
``/``, ``<placeholder>`` segments treated as wildcards.  Suppression uses
the qi-lint discipline (``# qi-lint: allow(rule) — reason``) at the
emitting call site; doc-side findings have no code line to suppress on and
must be fixed in the doc.
"""

from __future__ import annotations

import ast
import fnmatch
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analyze.lint import (
    FileContext,
    Finding,
    iter_python_files,
    name_arg_expr,
    resolve_name_arg,
    telemetry_calls,
)

SCHEMA = "qi-surface/1"
INVENTORY_PATH = Path(__file__).with_name("surface_inventory.json")

# Env-read extraction additionally covers tests/conftest.py: QI_TEST_PLATFORM
# is read there (the suite's platform pin) and nowhere else — the one
# infrastructure file outside the lint scan that legitimately consumes a
# declared knob.
ENV_EXTRA_SCAN = ("tests/conftest.py",)

_ENV_READERS = frozenset({"qi_env", "qi_env_flag", "qi_env_int", "qi_env_float"})


# ---------------------------------------------------------------------------
# extraction


class Emit:
    """One extracted emission site: ``name`` may end in ``*`` (wildcard
    from a dotted-prefix f-string)."""

    __slots__ = ("name", "path", "line")

    def __init__(self, name: str, path: str, line: int) -> None:
        self.name = name
        self.path = path
        self.line = line


class Surface:
    """The whole-program extraction (everything sorted-deterministic)."""

    def __init__(self) -> None:
        self.telemetry: Dict[str, List[Emit]] = {
            "counter": [], "gauge": [], "event": [], "span": [],
        }
        self.fault_fires: List[Emit] = []
        self.env_reads: List[Emit] = []
        self.schedules: List[str] = []
        self.wire: Dict[str, Dict[str, List[str]]] = {}
        # rel -> FileContext of every scanned file (suppression lookups)
        self.ctxs: Dict[str, FileContext] = {}

    def names(self, kind: str) -> Set[str]:
        return {e.name for e in self.telemetry[kind]}

    def to_inventory(self) -> Dict[str, object]:
        return {
            "schema": SCHEMA,
            "telemetry": {
                kind: sorted({e.name for e in emits})
                for kind, emits in sorted(self.telemetry.items())
            },
            "fault_points": sorted({e.name for e in self.fault_fires}),
            "env_reads": sorted({e.name for e in self.env_reads}),
            "schedules": sorted(self.schedules),
            "wire": {
                ch: {role: sorted(fields) for role, fields in sorted(spec.items())}
                for ch, spec in sorted(self.wire.items())
            },
        }


def _extract_file(ctx: FileContext, surface: Surface) -> None:
    for kind, names, node in telemetry_calls(ctx):
        for name in names:
            surface.telemetry[kind].append(Emit(name, ctx.rel, node.lineno))
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        arg = name_arg_expr(node)
        if arg is None:
            continue
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if fname == "fault_point":
            name = resolve_name_arg(ctx, arg)
            if name is not None:
                surface.fault_fires.append(Emit(name, ctx.rel, node.lineno))
        elif fname in _ENV_READERS or fname in ("getenv",) or (
            # bare os.environ.get("QI_X"): allowed only outside the lint
            # scan (tests/conftest.py reads the platform pin before the
            # package loads) — it is still a READ the unread-knob gate
            # must see.
            fname == "get" and isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Attribute)
            and f.value.attr == "environ"
        ):
            name = resolve_name_arg(ctx, arg)
            if name is not None and name.startswith("QI_"):
                surface.env_reads.append(Emit(name, ctx.rel, node.lineno))


def extract_surface(root: Path,
                    scan: Optional[Sequence[str]] = None) -> Surface:
    """Extract the full emission surface of the repo (AST only — nothing
    under scan is ever imported)."""
    surface = Surface()
    files = iter_python_files(root, scan)
    for extra in ENV_EXTRA_SCAN if scan is None else ():
        p = root / extra
        if p.is_file():
            files.append(p)
    for path in files:
        try:
            source = path.read_text(encoding="utf-8")
            ctx = FileContext(path, str(path.relative_to(root)), source)
        except (OSError, SyntaxError):
            continue  # the lint pass reports parse errors
        surface.ctxs[ctx.rel] = ctx
        _extract_file(ctx, surface)

    from tools.analyze import schedules as sched_mod

    surface.schedules = [
        *sched_mod.SCHEDULES, *sched_mod.SERVE_SCHEDULES,
        *sched_mod.DELTA_SCHEDULES, *sched_mod.FLEET_SCHEDULES,
        *sched_mod.FUSE_SCHEDULES,
    ]

    from tools.analyze.wire import extract_channels

    surface.wire = {
        ch.name: {"producer": sorted(ch.producer_fields),
                  "consumer": sorted(ch.consumer_fields)}
        for ch in extract_channels(root)
    }
    return surface


# ---------------------------------------------------------------------------
# registry parsing (docs tables — format frozen, see the docs' notes)

_BACKTICK_RE = re.compile(r"`([^`]+)`")


def _table_rows(text: str, heading: str) -> List[Tuple[int, List[str]]]:
    """``(lineno, cells)`` for each body row of the first markdown table
    after ``heading`` (cells stripped; header + separator rows skipped)."""
    lines = text.splitlines()
    rows: List[Tuple[int, List[str]]] = []
    in_section = False
    in_table = False
    for i, line in enumerate(lines, start=1):
        if line.strip().startswith("#"):
            if in_table:
                break
            in_section = line.strip().lstrip("#").strip().startswith(heading)
            continue
        if not in_section:
            continue
        if line.lstrip().startswith("|"):
            if not in_table:
                in_table = True
                continue  # header row
            cells = [c.strip() for c in line.strip().strip("|").split("|")]
            if cells and set(cells[0]) <= {"-", ":", " "}:
                continue  # separator row
            rows.append((i, cells))
        elif in_table:
            break  # table ended
    return rows


def _cell_names(cell: str) -> List[str]:
    """Backticked names in a table cell (``<x>`` placeholders → ``*``)."""
    out = []
    for name in _BACKTICK_RE.findall(cell):
        name = re.sub(r"<[^>]*>", "*", name).strip()
        if name:
            out.append(name)
    return out


class Registry:
    """One parsed doc registry: name → (doc_path, lineno)."""

    def __init__(self, doc: str) -> None:
        self.doc = doc
        self.entries: Dict[str, int] = {}

    def add(self, name: str, line: int) -> None:
        self.entries.setdefault(name, line)

    def names(self) -> Set[str]:
        return set(self.entries)


def parse_observability(root: Path) -> Dict[str, Registry]:
    """The OBSERVABILITY.md span / counter+gauge / event registries."""
    doc = "docs/OBSERVABILITY.md"
    text = (root / doc).read_text(encoding="utf-8")
    spans = Registry(doc)
    for line, cells in _table_rows(text, "Span inventory"):
        for name in _cell_names(cells[0] if cells else ""):
            spans.add(name, line)
    counters, gauges = Registry(doc), Registry(doc)
    for line, cells in _table_rows(text, "Counter / gauge inventory"):
        if len(cells) < 2:
            continue
        target = gauges if "gauge" in cells[1] else counters
        for name in _cell_names(cells[0]):
            target.add(name, line)
    events = Registry(doc)
    for line, cells in _table_rows(text, "Event inventory"):
        for name in _cell_names(cells[0] if cells else ""):
            events.add(name, line)
    return {"span": spans, "counter": counters, "gauge": gauges,
            "event": events}


def parse_robustness(root: Path) -> Tuple[Registry, Registry]:
    """``(fault_table, knob_table)`` from docs/ROBUSTNESS.md."""
    doc = "docs/ROBUSTNESS.md"
    text = (root / doc).read_text(encoding="utf-8")
    faults = Registry(doc)
    for line, cells in _table_rows(text, "Fault points"):
        for name in _cell_names(cells[0] if cells else ""):
            faults.add(name, line)
    knobs = Registry(doc)
    for line, cells in _table_rows(text, "Knobs"):
        for name in _cell_names(cells[0] if cells else ""):
            knobs.add(name, line)
    return faults, knobs


# ---------------------------------------------------------------------------
# wildcard matching

def _covered(name: str, names: Set[str]) -> bool:
    """Is ``name`` matched by ``names`` — exactly, via an fnmatch-style
    wildcard on the registry side (``a.*`` and the mid-name
    ``a.*.latency`` a ``<placeholder>`` row produces both work), or via a
    code-side wildcard (a dotted-prefix f-string) whose literal prefix
    intersects a registry entry?"""
    if name in names:
        return True
    if "*" not in name:
        return any(
            "*" in n and fnmatch.fnmatchcase(name, n) for n in names
        )
    # Code-side wildcard: match on the literal prefix before the first *.
    prefix = name.split("*", 1)[0]
    for n in names:
        if n == name:
            continue
        if "*" in n:
            other = n.split("*", 1)[0]
            if prefix.startswith(other) or other.startswith(prefix):
                return True
        elif n.startswith(prefix):
            return True
    return False


# ---------------------------------------------------------------------------
# the pass


def _declared_line(path: Path, literal: str) -> int:
    """Line of the first occurrence of ``"literal"`` in ``path`` (for
    pointing a declared-but-unused finding at the declaration)."""
    try:
        for i, line in enumerate(
                path.read_text(encoding="utf-8").splitlines(), start=1):
            if f'"{literal}"' in line:
                return i
    except OSError:
        pass
    return 1


def _suppressed(surface: Surface, rule: str, rel: str, line: int) -> bool:
    """qi-lint ``allow()`` lookup for code-side surface findings (doc-side
    rows have no code line to suppress on — fix the doc instead)."""
    ctx = surface.ctxs.get(rel)
    return ctx is not None and ctx.suppressed(rule, line)


def run_surface(root: Path, update_inventory: bool = False,
                scan: Optional[Sequence[str]] = None,
                inventory_path: Optional[Path] = None,
                declared_faults: Optional[Set[str]] = None,
                declared_env: Optional[Set[str]] = None,
                ) -> Tuple[List[Finding], List[str]]:
    """``(findings, notes)`` — the full surface pass: extraction, registry
    drift gates, and the committed-inventory stability gate.

    ``scan``/``inventory_path``/``declared_faults``/``declared_env`` exist
    for the fixture tests (tests/analyze_fixtures/surface/): they swap the
    scanned tree, the inventory file, and the runtime catalogs without
    touching the real ones.  Production callers pass only ``root``.
    """
    findings: List[Finding] = []
    notes: List[str] = []
    surface = extract_surface(root, scan)

    # -- telemetry names vs the OBSERVABILITY registries --------------------
    registries = parse_observability(root)
    for kind in ("counter", "gauge", "event", "span"):
        reg = registries[kind]
        reg_names = reg.names()
        code_names = surface.names(kind)
        flagged: Set[str] = set()
        for emit in surface.telemetry[kind]:
            if emit.name in flagged or _covered(emit.name, reg_names):
                continue
            if _suppressed(surface, "surface-telemetry-unregistered",
                           emit.path, emit.line):
                continue
            flagged.add(emit.name)
            findings.append(Finding(
                rule="surface-telemetry-unregistered", path=emit.path,
                line=emit.line,
                message=(
                    f"{kind} {emit.name!r} is emitted here but missing from "
                    f"the docs/OBSERVABILITY.md {kind} registry — add its "
                    f"row (the registry is the machine-parsed contract)"
                ),
            ))
        for name, line in sorted(reg.entries.items()):
            if not _covered(name, code_names):
                findings.append(Finding(
                    rule="surface-registry-stale", path=reg.doc, line=line,
                    message=(
                        f"registry row claims {kind} {name!r} but no code "
                        f"path emits it — delete the row or restore the "
                        f"emission"
                    ),
                ))

    # -- fault points: catalog vs fires vs the ROBUSTNESS table -------------
    if declared_faults is None:
        from quorum_intersection_tpu.utils import faults as faults_mod

        declared = set(faults_mod.registry())
    else:
        declared = set(declared_faults)
    fired = {e.name for e in surface.fault_fires}
    faults_path = root / "quorum_intersection_tpu/utils/faults.py"
    for name in sorted(declared - fired):
        decl_line = _declared_line(faults_path, name)
        if _suppressed(surface, "surface-fault-unfired",
                       "quorum_intersection_tpu/utils/faults.py", decl_line):
            continue
        findings.append(Finding(
            rule="surface-fault-unfired", path="quorum_intersection_tpu/utils/faults.py",
            line=decl_line,
            message=(
                f"fault point {name!r} is declared but no code path fires "
                f"it — an uninjectable boundary is dead robustness; wire a "
                f"fault_point({name!r}) call or drop the declaration"
            ),
        ))
    for emit in surface.fault_fires:
        if emit.name not in declared and not _suppressed(
                surface, "surface-fault-undeclared", emit.path, emit.line):
            findings.append(Finding(
                rule="surface-fault-undeclared", path=emit.path,
                line=emit.line,
                message=(
                    f"fault_point({emit.name!r}) is not in the "
                    f"utils/faults.py catalog (this call raises KeyError "
                    f"at runtime)"
                ),
            ))
    fault_table, knob_table = parse_robustness(root)
    for name, line in sorted(fault_table.entries.items()):
        if name not in declared:
            findings.append(Finding(
                rule="surface-fault-doc-stale", path=fault_table.doc,
                line=line,
                message=(
                    f"docs fault-table row {name!r} is not a declared "
                    f"fault point — delete the row or declare the point"
                ),
            ))
    for name in sorted(declared - fault_table.names()):
        findings.append(Finding(
            rule="surface-fault-undocumented", path=fault_table.doc, line=1,
            message=(
                f"declared fault point {name!r} has no row in the "
                f"docs/ROBUSTNESS.md fault table — the catalog and the "
                f"table must agree in both directions"
            ),
        ))

    # -- env knobs: registry vs reads vs the ROBUSTNESS knob table ----------
    if declared_env is None:
        from quorum_intersection_tpu.utils import env as env_mod

        declared_env = {v.name for v in env_mod.registry()}
    read_env = {e.name for e in surface.env_reads}
    env_path = root / "quorum_intersection_tpu/utils/env.py"
    for name in sorted(declared_env - read_env):
        decl_line = _declared_line(env_path, name)
        if _suppressed(surface, "surface-env-unread",
                       "quorum_intersection_tpu/utils/env.py", decl_line):
            continue
        findings.append(Finding(
            rule="surface-env-unread", path="quorum_intersection_tpu/utils/env.py",
            line=decl_line,
            message=(
                f"env knob {name!r} is declared but never read through "
                f"qi_env* — a knob nobody reads is documentation drift; "
                f"wire the read or drop the declaration"
            ),
        ))
    for emit in surface.env_reads:
        if emit.name not in declared_env and not _suppressed(
                surface, "surface-env-undeclared", emit.path, emit.line):
            findings.append(Finding(
                rule="surface-env-undeclared", path=emit.path, line=emit.line,
                message=(
                    f"qi_env read of undeclared knob {emit.name!r} (raises "
                    f"KeyError at runtime — declare it in utils/env.py)"
                ),
            ))
    for name, line in sorted(knob_table.entries.items()):
        if name.startswith("QI_") and name not in declared_env:
            findings.append(Finding(
                rule="surface-env-doc-stale", path=knob_table.doc, line=line,
                message=(
                    f"docs knob-table row {name!r} is not declared in "
                    f"utils/env.py — delete the row or declare the knob"
                ),
            ))

    # -- inventory stability -----------------------------------------------
    inv_path = inventory_path if inventory_path is not None else INVENTORY_PATH
    inventory = surface.to_inventory()
    rendered = json.dumps(inventory, indent=2, sort_keys=True) + "\n"
    committed = (
        inv_path.read_text(encoding="utf-8") if inv_path.exists() else ""
    )
    if update_inventory:
        if rendered != committed:
            inv_path.write_text(rendered, encoding="utf-8")
            notes.append(f"surface inventory updated: {inv_path}")
        else:
            notes.append("surface inventory already current")
    elif rendered != committed:
        findings.append(Finding(
            rule="surface-inventory-stale",
            path="tools/analyze/surface_inventory.json", line=1,
            message=(
                "committed qi-surface/1 inventory does not match a fresh "
                "extraction — the emission surface changed; regenerate "
                "with `python -m tools.analyze surface --update-inventory` "
                "and review the diff (wire-field renames, new telemetry, "
                "dropped fault points all land here)"
            ),
        ))

    notes.append(
        "surface: "
        f"{len(surface.names('counter'))} counters, "
        f"{len(surface.names('gauge'))} gauges, "
        f"{len(surface.names('event'))} events, "
        f"{len(surface.names('span'))} spans, "
        f"{len(fired)} fault points, {len(read_env)} env knobs, "
        f"{len(surface.schedules)} schedules, "
        f"{len(surface.wire)} wire channels"
    )
    return findings, notes
