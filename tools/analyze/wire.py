"""qi-wire: JSONL wire-schema conformance between producers and consumers.

The serve/fleet/query tier speaks four JSONL dialects — requests
(``qi-serve/1`` request lines + the nested ``qi-query/1`` object),
responses (verdict/error/replay/listening/pong lines), and the crash-only
request journal.  Each is produced in one module and consumed in another,
and nothing used to stop a producer rename (``"verdict"`` → ``"result"``)
from silently making every consumer read a default forever — the exact
skew class the fleet's cross-process pipes make invisible until a kill
round loses work.

This pass extracts, per **channel**, the field set each producer writes
(string keys of dict literals and ``obj["k"] = ...`` stores inside the
spec'd functions) and each consumer reads (``var.get("k")`` / ``var["k"]``
/ ``"k" in var`` on the spec'd variable names), then gates:

- **producer ⊇ consumer** — every field a consumer reads is written by
  some producer of the channel (``wire-consumer-unproduced``);
- **site integrity** — every spec'd producer/consumer function still
  exists and still touches the wire (``wire-site-missing`` /
  ``wire-site-empty``), so a refactor cannot silently move the protocol
  out from under the gate;
- **field stability** — the channel field sets land in the committed
  ``qi-surface/1`` inventory (tools/analyze/surface.py), so ANY field
  rename — including journal fields a replay must re-parse across a
  restart — is a reviewed inventory diff, not a silent skew.

Producer extraction over-approximates deliberately (every dict literal in
the function counts): a too-big producer set can only *weaken* the
consumer gate, never fail a clean tree; consumer extraction is restricted
to named variables so it stays exact.  Channel specs live in
:data:`CHANNEL_SPECS`; a new transport field needs no spec change unless a
new function joins the protocol.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from tools.analyze.lint import FileContext, Finding

Site = Tuple[str, int]  # (rel path, line)


@dataclass
class Channel:
    """One extracted wire channel."""

    name: str
    producer_fields: Dict[str, Site] = field(default_factory=dict)
    consumer_fields: Dict[str, Site] = field(default_factory=dict)
    findings: List[Finding] = field(default_factory=list)


# (channel, producers, consumers):
#   producer = (rel_path, qualname)
#   consumer = (rel_path, qualname, (var, ...))
CHANNEL_SPECS: Tuple[Tuple[str, Tuple[Tuple[str, str], ...],
                           Tuple[Tuple[str, str, Tuple[str, ...]], ...]], ...] = (
    (
        # Client → engine request lines (qi-serve/1): the fleet front door
        # is the in-repo producer; the transport seam parses them.
        "serve.request",
        (
            ("quorum_intersection_tpu/fleet.py", "ProcWorker.submit"),
            ("quorum_intersection_tpu/fleet.py", "ProcWorker.ping"),
            # qi-mesh (ISSUE 19): a socket-joined peer speaks the same
            # request dialect over TCP — hello handshake, submit/ping, and
            # the journal-ship pull + ack.
            ("quorum_intersection_tpu/fleet.py", "SocketWorker.__init__"),
            ("quorum_intersection_tpu/fleet.py", "SocketWorker.submit"),
            ("quorum_intersection_tpu/fleet.py", "SocketWorker.ping"),
            ("quorum_intersection_tpu/fleet.py", "SocketWorker.ship_journal"),
        ),
        (
            ("quorum_intersection_tpu/serve_transport.py",
             "JsonlSession.handle_line", ("obj",)),
            ("quorum_intersection_tpu/serve_transport.py",
             "JsonlSession._handle_hello", ("hello", "store")),
            ("quorum_intersection_tpu/serve_transport.py",
             "JsonlSession._handle_ship", ("ship",)),
        ),
    ),
    (
        # Engine → client response lines: verdicts, typed errors, replay
        # reports, the listening announcement, and pong health snapshots;
        # the fleet's reader demux is the consumer.
        "serve.response",
        (
            ("quorum_intersection_tpu/serve_transport.py", "ticket_response"),
            ("quorum_intersection_tpu/serve_transport.py",
             "JsonlSession.handle_line"),
            ("quorum_intersection_tpu/serve_transport.py", "pong_payload"),
            ("quorum_intersection_tpu/serve_transport.py", "serve_main"),
            ("quorum_intersection_tpu/serve.py",
             "ServeEngine._replay_journal"),
            # qi-mesh (ISSUE 19): handshake replies + chunked journal
            # shipping ride the response stream back to the joining fleet.
            ("quorum_intersection_tpu/serve_transport.py",
             "JsonlSession._handle_hello"),
            ("quorum_intersection_tpu/serve_transport.py",
             "JsonlSession._handle_ship"),
        ),
        (
            ("quorum_intersection_tpu/fleet.py", "ProcWorker._read_loop",
             ("obj",)),
            ("quorum_intersection_tpu/fleet.py", "SocketWorker._read_loop",
             ("obj", "ok")),
            ("quorum_intersection_tpu/fleet.py",
             "SocketWorker._collect_chunk", ("chunk",)),
            ("quorum_intersection_tpu/fleet.py", "SocketWorker.ship_journal",
             ("end",)),
            ("quorum_intersection_tpu/fleet.py", "FleetEngine._on_response",
             ("obj", "err")),
            ("quorum_intersection_tpu/fleet.py",
             "FleetEngine._aggregate_health", ("pong",)),
            # qi-pulse (ISSUE 15): the aggregation plane reads the pong's
            # histogram snapshots — a renamed "pulse" field must fail the
            # producer ⊇ consumer gate, not silently stall the fleet view.
            ("quorum_intersection_tpu/fleet.py",
             "FleetEngine._aggregate_pulse", ("pong",)),
        ),
    ),
    (
        # The nested qi-query/1 object riding a request's "query" field:
        # Query.to_wire is the canonical producer (the CLI builds the same
        # shape), Query.parse the one consumer everywhere.
        "query",
        (
            ("quorum_intersection_tpu/query.py", "Query.to_wire"),
            ("quorum_intersection_tpu/query.py", "query_main"),
        ),
        (
            ("quorum_intersection_tpu/query.py", "Query.parse", ("raw",)),
        ),
    ),
    (
        # qi-store/1 client → gateway lines (qi-mesh, ISSUE 19): the
        # store_hello session opener plus get/put fragment ops a socket
        # worker sends to the front door's StoreGateway.
        "store.request",
        (
            ("quorum_intersection_tpu/delta.py",
             "RemoteStoreClient._connect_locked"),
            ("quorum_intersection_tpu/delta.py", "RemoteStoreClient.fetch"),
            ("quorum_intersection_tpu/delta.py",
             "RemoteStoreClient.publish"),
        ),
        (
            ("quorum_intersection_tpu/fleet.py", "StoreGateway._serve_conn",
             ("hello", "inner", "op")),
        ),
    ),
    (
        # qi-store/1 gateway → client lines: one {"ok": ...} answer per
        # op; the client's retry loop and fetch path parse them.
        "store.response",
        (
            ("quorum_intersection_tpu/fleet.py", "StoreGateway._serve_conn"),
        ),
        (
            ("quorum_intersection_tpu/delta.py",
             "RemoteStoreClient._connect_locked", ("resp",)),
            ("quorum_intersection_tpu/delta.py", "RemoteStoreClient._request",
             ("resp",)),
            ("quorum_intersection_tpu/delta.py", "RemoteStoreClient.fetch",
             ("resp",)),
        ),
    ),
    (
        # The crash-only request journal (qi-serve-journal/1): replay
        # across a restart — and across a dead fleet worker's inheritance
        # — must re-parse exactly what the appenders wrote.
        "serve.journal",
        (
            ("quorum_intersection_tpu/serve.py",
             "RequestJournal._append_line"),
            ("quorum_intersection_tpu/serve.py",
             "RequestJournal.append_request"),
            ("quorum_intersection_tpu/serve.py",
             "RequestJournal.append_done"),
            ("quorum_intersection_tpu/serve.py", "RequestJournal.compact"),
        ),
        (
            ("quorum_intersection_tpu/serve.py", "RequestJournal.scan",
             ("obj",)),
            ("quorum_intersection_tpu/serve.py",
             "ServeEngine._replay_journal", ("e",)),
            ("quorum_intersection_tpu/fleet.py", "FleetEngine._failover",
             ("e", "entry")),
        ),
    ),
)


# ---------------------------------------------------------------------------
# function lookup + field extraction


def _find_function(tree: ast.Module, qualname: str) -> Optional[ast.FunctionDef]:
    parts = qualname.split(".")
    body: Sequence[ast.stmt] = tree.body
    for i, part in enumerate(parts):
        found = None
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == part and i == len(parts) - 1:
                return node
            if isinstance(node, ast.ClassDef) and node.name == part:
                found = node
                break
        if found is None:
            return None
        body = found.body
    return None


def _producer_fields(fn: ast.AST, rel: str) -> Dict[str, Site]:
    out: Dict[str, Site] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for key in node.keys:
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    out.setdefault(key.value, (rel, key.lineno))
        elif isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Subscript) \
                        and isinstance(tgt.slice, ast.Constant) \
                        and isinstance(tgt.slice.value, str):
                    out.setdefault(tgt.slice.value, (rel, tgt.lineno))
    return out


def _consumer_fields(fn: ast.AST, rel: str,
                     varnames: Sequence[str]) -> Dict[str, Site]:
    names = set(varnames)
    out: Dict[str, Site] = {}

    def is_wire_var(node: ast.AST) -> bool:
        return isinstance(node, ast.Name) and node.id in names

    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" and is_wire_var(node.func.value) \
                and node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            out.setdefault(node.args[0].value, (rel, node.lineno))
        elif isinstance(node, ast.Subscript) and is_wire_var(node.value) \
                and isinstance(node.ctx, ast.Load) \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            out.setdefault(node.slice.value, (rel, node.lineno))
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and isinstance(node.left, ast.Constant) \
                and isinstance(node.left.value, str) \
                and node.comparators and is_wire_var(node.comparators[0]):
            out.setdefault(node.left.value, (rel, node.lineno))
    return out


# ---------------------------------------------------------------------------
# the pass


def _load_ctx(root: Path, rel: str,
              cache: Dict[str, Optional[FileContext]]) -> Optional[FileContext]:
    if rel not in cache:
        try:
            source = (root / rel).read_text(encoding="utf-8")
            cache[rel] = FileContext(root / rel, rel, source)
        except (OSError, SyntaxError):
            cache[rel] = None
    return cache[rel]


def extract_channels(root: Path) -> List[Channel]:
    """Extract every spec'd channel (site-integrity findings attached)."""
    cache: Dict[str, Optional[FileContext]] = {}
    channels: List[Channel] = []
    for name, producers, consumers in CHANNEL_SPECS:
        ch = Channel(name)
        for rel, qualname in producers:
            ctx = _load_ctx(root, rel, cache)
            fn = _find_function(ctx.tree, qualname) if ctx else None
            if fn is None:
                ch.findings.append(Finding(
                    rule="wire-site-missing", path=rel, line=1,
                    message=(
                        f"wire channel {name!r} producer {qualname!r} not "
                        f"found — update tools/analyze/wire.py "
                        f"CHANNEL_SPECS so the protocol stays gated"
                    ),
                ))
                continue
            fields = _producer_fields(fn, rel)
            if not fields:
                ch.findings.append(Finding(
                    rule="wire-site-empty", path=rel, line=fn.lineno,
                    message=(
                        f"wire channel {name!r} producer {qualname!r} "
                        f"writes no statically visible fields — the gate "
                        f"is checking nothing; fix the spec or the function"
                    ),
                ))
            for f_name, site in fields.items():
                ch.producer_fields.setdefault(f_name, site)
        for rel, qualname, varnames in consumers:
            ctx = _load_ctx(root, rel, cache)
            fn = _find_function(ctx.tree, qualname) if ctx else None
            if fn is None:
                ch.findings.append(Finding(
                    rule="wire-site-missing", path=rel, line=1,
                    message=(
                        f"wire channel {name!r} consumer {qualname!r} not "
                        f"found — update tools/analyze/wire.py "
                        f"CHANNEL_SPECS so the protocol stays gated"
                    ),
                ))
                continue
            fields = _consumer_fields(fn, rel, varnames)
            if not fields:
                ch.findings.append(Finding(
                    rule="wire-site-empty", path=rel, line=fn.lineno,
                    message=(
                        f"wire channel {name!r} consumer {qualname!r} reads "
                        f"no fields from {'/'.join(varnames)} — the gate is "
                        f"checking nothing; fix the spec or the function"
                    ),
                ))
            for f_name, site in fields.items():
                ch.consumer_fields.setdefault(f_name, site)
        channels.append(ch)
    return channels


def run_wire(root: Path) -> Tuple[List[Finding], List[str]]:
    """``(findings, notes)``: producer ⊇ consumer per channel, plus the
    site-integrity findings from extraction."""
    findings: List[Finding] = []
    notes: List[str] = []
    cache: Dict[str, Optional[FileContext]] = {}
    for ch in extract_channels(root):
        findings.extend(ch.findings)
        for f_name, (rel, line) in sorted(ch.consumer_fields.items()):
            if f_name in ch.producer_fields:
                continue
            ctx = _load_ctx(root, rel, cache)
            if ctx is not None and ctx.suppressed("wire-consumer-unproduced",
                                                  line):
                continue
            findings.append(Finding(
                rule="wire-consumer-unproduced", path=rel, line=line,
                message=(
                    f"wire channel {ch.name!r}: consumer reads field "
                    f"{f_name!r} that no producer of the channel writes — "
                    f"a renamed/dropped protocol field reads a default "
                    f"forever; fix the producer or the consumer"
                ),
            ))
        notes.append(
            f"wire {ch.name}: {len(ch.producer_fields)} produced, "
            f"{len(ch.consumer_fields)} consumed fields"
        )
    return findings, notes
