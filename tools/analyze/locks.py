"""qi-locks: interprocedural lockset + lock-order analysis (Eraser lineage).

The per-file ``lock-discipline`` lint rule polices the telemetry record's
own guarded attributes; since PRs 8-12 the threaded surface is much wider
— the serve engine's drain/supervisor threads, the fleet's reader/probe/
respawn threads, the delta store's single-flight leases — and the race
harness (tools/analyze/schedules.py) only *samples* those interleavings
dynamically.  This pass analyzes them statically, whole-program, over
:data:`TARGETS`:

- **Lock model**: every ``self.X = threading.Lock()/RLock()/Condition()``
  (and module-level twin) becomes a lock identity; ``Condition(self.Y)``
  aliases to ``Y`` (they are one lock); ``threading.Event()`` attrs are
  tracked for blocking-call detection; ``Thread(...)`` attrs/locals for
  join detection.
- **Lock-order graph** (``lock-order-cycle``): a ``with`` acquisition or a
  *call into a function that acquires* while already holding a lock adds
  an order edge, call edges resolved interprocedurally (``self.m()``,
  module functions, cross-module imports within the target set,
  unique-method-name fallback, and run-record emission calls — which take
  ``RunRecord._lock``).  Any cycle — including a self-edge, which is a
  non-reentrant re-acquisition deadlock — is a finding.
- **Blocking under a lock** (``lock-blocking``): ``Thread.join``,
  ``Event.wait``/``Condition.wait`` (except the sanctioned wait on the
  innermost held lock's own condition), ``subprocess.run``/``Popen``/
  ``communicate``, ``os.fsync`` and ``time.sleep`` reached while a lock is
  held stall every thread parked on that lock.
- **Guardian locksets** (``lock-guardian``): per class attribute, the
  intersection of locks held across its mutation sites (``__init__``
  exempt; a helper only ever called under a lock inherits that lock via
  the intersection of its observed call sites).  An attribute mutated
  under a lock somewhere but reachable lock-free from a ``Thread`` target
  (or a registered callback — those run on other threads here) has an
  empty guardian and a real interleaving that loses the write.

The analysis is deliberately conservative where it cannot resolve (unknown
receivers are skipped, not guessed), so a finding is worth reading;
suppress a reviewed one with ``# qi-lint: allow(rule) — reason`` on the
flagged line, like every other rule.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from tools.analyze.callgraph import (
    CallGraph,
    CallRef,
    FnKey,
    collect_imports,
    ctor_name,
    iter_defs,
    module_rel_map,
    ref_of,
)
from tools.analyze.callgraph import threading_call as _is_threading_call
from tools.analyze.lint import FileContext, Finding, _looks_like_record

# The heavily-threaded surface this pass covers (ISSUE 13).
TARGETS = (
    "quorum_intersection_tpu/serve.py",
    "quorum_intersection_tpu/serve_transport.py",
    "quorum_intersection_tpu/fleet.py",
    "quorum_intersection_tpu/delta.py",
    "quorum_intersection_tpu/backends/auto.py",
    "quorum_intersection_tpu/utils/telemetry.py",
    "quorum_intersection_tpu/utils/metrics_server.py",
)

RECORD_LOCK = "quorum_intersection_tpu/utils/telemetry.py:RunRecord._lock"
_RECORD_METHODS = frozenset({
    "add", "gauge", "event", "declare", "snapshot", "span", "event_count",
    "events_since", "events_truncated", "add_sink",
})
_MUTATING_METHODS = frozenset({
    "append", "appendleft", "add", "setdefault", "update", "pop", "popleft",
    "clear", "extend", "remove", "discard", "insert",
})
_SUBPROCESS_BLOCKING = frozenset({
    "run", "call", "check_call", "check_output",
})

@dataclass
class ClassModel:
    """Lock/event/thread attribute kinds of one class."""

    name: str
    rel: str
    locks: Dict[str, str] = field(default_factory=dict)    # attr -> lock id
    aliases: Dict[str, str] = field(default_factory=dict)  # cond attr -> lock attr
    reentrant: Set[str] = field(default_factory=set)       # RLock ids
    conditions: Set[str] = field(default_factory=set)
    events: Set[str] = field(default_factory=set)
    threads: Set[str] = field(default_factory=set)
    methods: Set[str] = field(default_factory=set)
    # attr -> class name it is constructed from (``self.X = ClassName(...)``)
    instances: Dict[str, str] = field(default_factory=dict)

    def lock_id(self, attr: str) -> Optional[str]:
        attr = self.aliases.get(attr, attr)
        return self.locks.get(attr)


@dataclass
class FnModel:
    """One analyzed function/method body."""

    key: FnKey
    cls: Optional[ClassModel]
    node: ast.AST
    # (held-before frozenset, acquired lock id, line)
    acquisitions: List[Tuple[FrozenSet[str], str, int]] = field(default_factory=list)
    # (held frozenset, callee key-or-None spec, line)
    calls: List[Tuple[FrozenSet[str], "CallRef", int]] = field(default_factory=list)
    # (attr, held frozenset, line)  — self-attr mutations (not __init__)
    mutations: List[Tuple[str, FrozenSet[str], int]] = field(default_factory=list)
    # (description, held frozenset, line, condition-lock-or-None) — every
    # candidate blocking op, judged against held ∪ entry_held at report
    # time so a *_locked helper's sleep/fsync is still caught
    blocking: List[Tuple[str, FrozenSet[str], int, Optional[str]]] = field(
        default_factory=list)
    # function refs spawned as threads / registered as callbacks
    thread_refs: List["CallRef"] = field(default_factory=list)
    entry_held: FrozenSet[str] = frozenset()
    entry_seen: bool = False
    # Union of held sets over observed entry contexts: nonempty while
    # entry_held (the intersection) is empty means the function is
    # reached BOTH under a lock and lock-free — mixed-context evidence
    # the guardian check must not ignore.
    entry_union: FrozenSet[str] = frozenset()


class Model(CallGraph):
    """Whole-program model over the target files.

    Call-edge resolution (``resolve``) is inherited from the shared
    :class:`tools.analyze.callgraph.CallGraph`; this subclass adds the
    lock-specific state.
    """

    def __init__(self) -> None:
        super().__init__()
        self.classes: Dict[Tuple[str, str], ClassModel] = {}
        self.functions: Dict[FnKey, FnModel] = {}
        self.module_locks: Dict[Tuple[str, str], str] = {}
        self.reentrant: Set[str] = set()  # RLock ids (legal re-acquisition)


# ---------------------------------------------------------------------------
# model construction


def _scan_class(rel: str, cls: ast.ClassDef) -> ClassModel:
    model = ClassModel(name=cls.name, rel=rel)
    for node in ast.walk(cls):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if any(n is node for n in cls.body):
                model.methods.add(node.name)
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            continue
        kind = _is_threading_call(
            node.value, ("Lock", "RLock", "Condition", "Event", "Thread"))
        if kind in ("Lock", "RLock"):
            lock_id = f"{rel}:{cls.name}.{tgt.attr}"
            model.locks[tgt.attr] = lock_id
            if kind == "RLock":
                model.reentrant.add(lock_id)
        elif kind == "Condition":
            model.conditions.add(tgt.attr)
            args = node.value.args if isinstance(node.value, ast.Call) else []
            if args and isinstance(args[0], ast.Attribute) \
                    and isinstance(args[0].value, ast.Name) \
                    and args[0].value.id == "self":
                model.aliases[tgt.attr] = args[0].attr
            else:
                model.locks[tgt.attr] = f"{rel}:{cls.name}.{tgt.attr}"
        elif kind == "Event":
            model.events.add(tgt.attr)
        elif kind == "Thread":
            model.threads.add(tgt.attr)
        elif kind is None:
            ctor = ctor_name(node.value)
            if ctor is not None:
                model.instances[tgt.attr] = ctor
    return model


class _FnScanner:
    """Walk one function body tracking the syntactically held lock set."""

    def __init__(self, model: Model, fn: FnModel, ctx: FileContext) -> None:
        self.model = model
        self.fn = fn
        self.ctx = ctx
        self.local_threads: Set[str] = set()
        self.local_events: Set[str] = set()

    # -- lock expr resolution ------------------------------------------------

    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name):
            if expr.value.id == "self" and self.fn.cls is not None:
                return self.fn.cls.lock_id(expr.attr)
            # module-qualified or foreign receiver: unique-attr fallback
            owners = [
                c for c in self.model.classes.values()
                if c.lock_id(expr.attr) is not None
            ]
            if len(owners) == 1:
                return owners[0].lock_id(expr.attr)
            return None
        if isinstance(expr, ast.Name):
            return self.model.module_locks.get((self.fn.key[0], expr.id))
        return None

    def _cond_lock_of(self, expr: ast.AST) -> Optional[str]:
        """Lock id of a condition receiver (for the sanctioned-wait check)."""
        return self._lock_of(expr)

    # -- walking -------------------------------------------------------------

    def scan(self) -> None:
        body = getattr(self.fn.node, "body", [])
        for stmt in body:
            self._visit(stmt, frozenset())

    def _visit(self, node: ast.AST, held: FrozenSet[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # nested defs are modeled as their own functions
        if isinstance(node, ast.With):
            inner = held
            for item in node.items:
                self._visit(item.context_expr, held)
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    self.fn.acquisitions.append((inner, lock, node.lineno))
                    inner = inner | {lock}
            for child in node.body:
                self._visit(child, inner)
            return
        if isinstance(node, ast.Assign):
            self._note_locals(node)
            self._note_mutation(node, held)
        elif isinstance(node, ast.AugAssign):
            self._note_mutation(node, held)
        elif isinstance(node, ast.Call):
            self._note_call(node, held)
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)

    def _note_locals(self, node: ast.Assign) -> None:
        kind = _is_threading_call(node.value, ("Thread", "Event"))
        if kind is None or len(node.targets) != 1 \
                or not isinstance(node.targets[0], ast.Name):
            return
        tgt = node.targets[0].id
        (self.local_threads if kind == "Thread" else self.local_events).add(tgt)

    def _note_mutation(self, node: ast.AST, held: FrozenSet[str]) -> None:
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Subscript):
                tgt = tgt.value
            if isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name) \
                    and tgt.value.id == "self":
                self.fn.mutations.append((tgt.attr, held, tgt.lineno))

    def _thread_like(self, recv: ast.AST) -> bool:
        if isinstance(recv, ast.Attribute):
            attr = recv.attr
            if self.fn.cls is not None and attr in self.fn.cls.threads:
                return True
            return "thread" in attr.lower() or "worker" in attr.lower() \
                or "proc" in attr.lower()
        if isinstance(recv, ast.Name):
            return recv.id in self.local_threads \
                or "thread" in recv.id.lower() or "worker" in recv.id.lower() \
                or "proc" in recv.id.lower()
        return False

    def _note_blocking(self, node: ast.Call, held: FrozenSet[str]) -> None:
        # Candidates are recorded even with an empty syntactic held set:
        # a helper only ever called under a lock inherits that lock via
        # entry_held, and its sleep/fsync must still be a finding.
        f = node.func
        if isinstance(f, ast.Attribute):
            recv, attr = f.value, f.attr
            if attr == "join" and self._thread_like(recv):
                self.fn.blocking.append(
                    ("Thread.join", held, node.lineno, None))
            elif attr in ("wait", "wait_for"):
                # The condition's lock rides along so the sanctioned
                # wait-on-the-only-held-lock pattern can be recognized
                # against the EFFECTIVE held set at report time.
                cond_lock = self._cond_lock_of(recv)
                self.fn.blocking.append(
                    (f"{attr}() on a gate/condition", held, node.lineno,
                     cond_lock))
            elif attr == "communicate":
                self.fn.blocking.append(
                    ("subprocess communicate", held, node.lineno, None))
            elif attr == "fsync":
                self.fn.blocking.append(("fsync", held, node.lineno, None))
            elif attr == "sleep" and isinstance(recv, ast.Name) \
                    and recv.id == "time":
                self.fn.blocking.append(
                    ("time.sleep", held, node.lineno, None))
            elif attr in _SUBPROCESS_BLOCKING and isinstance(recv, ast.Name) \
                    and recv.id == "subprocess":
                self.fn.blocking.append(
                    (f"subprocess.{attr}", held, node.lineno, None))

    def _ref_of(self, expr: ast.AST) -> Optional[CallRef]:
        cls = self.fn.cls.name if self.fn.cls is not None else None
        instances = self.fn.cls.instances if self.fn.cls is not None else {}
        return ref_of(expr, self.fn.key[0], cls, instances)

    def _note_call(self, node: ast.Call, held: FrozenSet[str]) -> None:
        self._note_blocking(node, held)
        f = node.func
        # Mutating container-method calls on a self attribute count as
        # mutations of that attribute (``self.items.append(x)``).
        if isinstance(f, ast.Attribute) and f.attr in _MUTATING_METHODS \
                and isinstance(f.value, ast.Attribute) \
                and isinstance(f.value.value, ast.Name) \
                and f.value.value.id == "self":
            self.fn.mutations.append((f.value.attr, held, node.lineno))
        # Run-record emission: takes RunRecord._lock (the order edge the
        # per-file lint rule cannot see).
        if isinstance(f, ast.Attribute) and f.attr in _RECORD_METHODS \
                and _looks_like_record(self.ctx, f.value):
            if any(c.rel.endswith("utils/telemetry.py")
                   for c in self.model.classes.values()):
                self.fn.acquisitions.append((held, RECORD_LOCK, node.lineno))
            return
        ref = self._ref_of(f)
        if ref is not None:
            self.fn.calls.append((held, ref, node.lineno))
        # Thread targets + registered callbacks run on other threads.
        spawn = _is_threading_call(node, ("Thread",))
        if spawn:
            for kw in node.keywords:
                if kw.arg == "target":
                    tref = self._ref_of(kw.value)
                    if tref is not None:
                        self.fn.thread_refs.append(tref)
        else:
            for arg in node.args:
                if isinstance(arg, (ast.Attribute, ast.Name)):
                    tref = self._ref_of(arg)
                    if tref is not None and self.model.resolve(tref) is not None:
                        self.fn.thread_refs.append(tref)


def build_model(root: Path, targets: Sequence[str]) -> Model:
    model = Model()
    trees: List[Tuple[str, ast.Module, FileContext]] = []
    for rel in targets:
        path = root / rel
        if not path.is_file():
            continue
        try:
            source = path.read_text(encoding="utf-8")
            ctx = FileContext(path, rel, source)
        except (OSError, SyntaxError):
            continue
        model.ctxs[rel] = ctx
        trees.append((rel, ctx.tree, ctx))
    rel_by_module = module_rel_map(rel for rel, _, _ in trees)
    # pass 1: classes, module locks/functions, imports
    for rel, tree, _ in trees:
        model.module_fns[rel] = set()
        model.imports.update(collect_imports(rel, tree, rel_by_module))
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                cls_model = _scan_class(rel, node)
                model.classes[(rel, node.name)] = cls_model
                model.reentrant |= cls_model.reentrant
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                model.module_fns[rel].add(node.name)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                kind = _is_threading_call(node.value, ("Lock", "RLock"))
                if kind:
                    name = node.targets[0].id
                    model.module_locks[(rel, name)] = f"{rel}:{name}"
                    if kind == "RLock":
                        model.reentrant.add(f"{rel}:{name}")
    # pass 2: function bodies (methods, module functions, nested defs —
    # registration scheme shared with the other passes via iter_defs)
    for rel, tree, ctx in trees:
        for qual, cls_name, fn_node in iter_defs(tree):
            cls_model = model.classes.get((rel, cls_name)) \
                if cls_name is not None else None
            fn = FnModel(key=(rel, qual), cls=cls_model, node=fn_node)
            model.functions[fn.key] = fn
    # method-name index for unique-name resolution
    for key, fn in model.functions.items():
        model.method_index.setdefault(key[1].split(".")[-1], []).append(key)
    # pass 3: scan bodies
    for fn in list(model.functions.values()):
        _FnScanner(model, fn, model.ctxs[fn.key[0]]).scan()
    return model


# ---------------------------------------------------------------------------
# interprocedural propagation


def _propagate_entry_held(model: Model, rounds: Optional[int] = None) -> None:
    """entry_held(f) = intersection of held sets over every observed call
    site — the static twin of the repo's ``*_locked`` helper convention.
    A function spawned as a Thread target (or registered as a callback)
    ALSO starts with nothing held: that entry point contributes an empty
    set to the intersection, so a probe/worker loop called both inline
    under a lock and from its own thread is never unsoundly exempted
    from the guardian check."""
    thread_roots: Set[FnKey] = set()
    for fn in model.functions.values():
        for ref in fn.thread_refs:
            resolved = model.resolve(ref)
            if resolved is not None:
                thread_roots.add(resolved)
    # Iterate to convergence: a 4-deep *_locked helper chain needs 4
    # rounds to inherit the lock — a fixed small cap would silently drop
    # the context (and the finding).  Function count bounds the longest
    # acyclic call chain, so this always terminates.
    if rounds is None:
        rounds = max(len(model.functions), 8)
    for _ in range(rounds):
        observed: Dict[FnKey, Optional[FrozenSet[str]]] = {
            key: frozenset() for key in thread_roots
        }
        unions: Dict[FnKey, FrozenSet[str]] = {
            key: frozenset() for key in thread_roots
        }
        for fn in model.functions.values():
            base = fn.entry_held
            for held, ref, _line in fn.calls:
                callee = model.resolve(ref)
                if callee is None:
                    continue
                eff = held | base
                prev = observed.get(callee)
                observed[callee] = eff if prev is None else (prev & eff)
                unions[callee] = unions.get(callee, frozenset()) | eff
        changed = False
        for key, inter in observed.items():
            fn = model.functions[key]
            new = inter or frozenset()
            fn.entry_union = unions.get(key, frozenset())
            if not fn.entry_seen or new != fn.entry_held:
                fn.entry_seen = True
                if new != fn.entry_held:
                    fn.entry_held = new
                    changed = True
        if not changed:
            break


def _transitive_acquires(model: Model) -> Dict[FnKey, Set[str]]:
    acq: Dict[FnKey, Set[str]] = {
        key: {lock for _, lock, _ in fn.acquisitions}
        for key, fn in model.functions.items()
    }
    changed = True
    while changed:
        changed = False
        for key, fn in model.functions.items():
            for _, ref, _line in fn.calls:
                callee = model.resolve(ref)
                if callee is None:
                    continue
                before = len(acq[key])
                acq[key] |= acq[callee]
                if len(acq[key]) != before:
                    changed = True
    return acq


def _thread_reachable(model: Model) -> Set[FnKey]:
    roots: Set[FnKey] = set()
    for fn in model.functions.values():
        for ref in fn.thread_refs:
            resolved = model.resolve(ref)
            if resolved is not None:
                roots.add(resolved)
    reachable = set(roots)
    frontier = list(roots)
    while frontier:
        key = frontier.pop()
        for _, ref, _line in model.functions[key].calls:
            callee = model.resolve(ref)
            if callee is not None and callee not in reachable:
                reachable.add(callee)
                frontier.append(callee)
    return reachable


# ---------------------------------------------------------------------------
# findings


def _emit(ctx: Optional[FileContext], rule: str, rel: str, line: int,
          message: str, findings: List[Finding]) -> bool:
    if ctx is not None and ctx.suppressed(rule, line):
        return False
    findings.append(Finding(rule=rule, path=rel, line=line, message=message))
    return True


def _order_cycles(model: Model, findings: List[Finding]) -> int:
    acq = _transitive_acquires(model)
    edges: Dict[str, Dict[str, Tuple[str, int, str]]] = {}

    def add_edge(a: str, b: str, rel: str, line: int, why: str) -> None:
        edges.setdefault(a, {}).setdefault(b, (rel, line, why))

    for key, fn in model.functions.items():
        base = fn.entry_held
        for held, lock, line in fn.acquisitions:
            for h in held | base:
                if h != lock:
                    add_edge(h, lock, key[0], line, f"in {key[1]}")
                elif h == lock and lock != RECORD_LOCK \
                        and lock not in model.reentrant:
                    # re-acquisition of a non-reentrant lock (RLocks may
                    # legally re-enter — that is what they are for)
                    add_edge(h, lock, key[0], line, f"re-entry in {key[1]}")
        for held, ref, line in fn.calls:
            callee = model.resolve(ref)
            if callee is None:
                continue
            for h in held | base:
                for lock in acq[callee]:
                    if h == lock and (lock == RECORD_LOCK
                                      or lock in model.reentrant):
                        continue  # re-entrant / the record's own re-reads
                    add_edge(h, lock, key[0], line,
                             f"{key[1]} calls {callee[1]}")

    # cycle detection (DFS over the order graph)
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    reported: Set[FrozenSet[str]] = set()
    count = 0

    def dfs(node: str, stack: List[str]) -> None:
        nonlocal count
        color[node] = GRAY
        stack.append(node)
        for nxt, (rel, line, why) in sorted(edges.get(node, {}).items()):
            if color.get(nxt, WHITE) == GRAY:
                cycle = stack[stack.index(nxt):] + [nxt]
                ident = frozenset(cycle)
                if ident not in reported:
                    reported.add(ident)
                    ctx = model.ctxs.get(rel)
                    count += _emit(
                        ctx, "lock-order-cycle", rel, line,
                        "lock-acquisition-order cycle (potential deadlock): "
                        + " -> ".join(c.split(":")[-1] for c in cycle)
                        + f" ({why}); acquire these locks in one global "
                          f"order or copy data out and release first",
                        findings,
                    )
            elif color.get(nxt, WHITE) == WHITE:
                dfs(nxt, stack)
        stack.pop()
        color[node] = BLACK

    for node in sorted(edges):
        if color.get(node, WHITE) == WHITE:
            dfs(node, [])
    return count


def _blocking_findings(model: Model, findings: List[Finding]) -> int:
    count = 0
    for key, fn in model.functions.items():
        base = fn.entry_held
        for desc, held, line, cond_lock in fn.blocking:
            eff = held | base
            if not eff:
                continue
            if cond_lock is not None and eff == {cond_lock}:
                continue  # the sanctioned wait on the only held lock
            locks = ", ".join(sorted(h.split(":")[-1] for h in eff))
            count += _emit(
                model.ctxs.get(key[0]), "lock-blocking", key[0], line,
                f"blocking call ({desc}) while holding {locks} in "
                f"{key[1]}: every thread parked on that lock stalls for "
                f"the full wait — move the blocking work outside the "
                f"critical section",
                findings,
            )
    return count


def _guardian_findings(model: Model, findings: List[Finding]) -> int:
    reachable = _thread_reachable(model)
    count = 0
    # (rel, class, attr) -> [(fnkey, heldset, line)]
    sites: Dict[Tuple[str, str, str], List[Tuple[FnKey, FrozenSet[str], int]]] = {}
    for key, fn in model.functions.items():
        if fn.cls is None:
            continue
        method = key[1].split(".")[-1]
        if method == "__init__":
            continue
        for attr, held, line in fn.mutations:
            sites.setdefault((key[0], fn.cls.name, attr), []).append(
                (key, held | fn.entry_held, line))
    for (rel, cls_name, attr), attr_sites in sorted(sites.items()):
        locksets = [held for _, held, _ in attr_sites]
        # Evidence a guardian was ever claimed: a site holding a lock, OR
        # a site in a function reached under a lock in SOME context
        # (mixed entry — the thread-target-plus-locked-call case where
        # the per-site intersection is already empty).
        claimed = any(locksets) or any(
            not held and model.functions[key].entry_union
            for key, held, _ in attr_sites
        )
        if not claimed:
            continue  # no guardian ever claimed — not a discipline drift
        guardian = frozenset.intersection(*locksets)
        if guardian:
            continue  # a consistent guardian lock exists
        for key, held, line in attr_sites:
            if held:
                continue
            if key not in reachable:
                continue
            count += _emit(
                model.ctxs.get(rel), "lock-guardian", rel, line,
                f"attribute {cls_name}.{attr} is mutated under a lock "
                f"elsewhere but lock-free here in {key[1]}, which is "
                f"reachable from a Thread target — a concurrent "
                f"interleaving can lose this write; take the guardian "
                f"lock (or suppress with the reason it is single-threaded)",
                findings,
            )
    return count


def run_locks(root: Path, targets: Optional[Sequence[str]] = None,
              ) -> Tuple[List[Finding], List[str]]:
    """``(findings, notes)`` — the whole-program lock analysis."""
    model = build_model(root, targets if targets is not None else TARGETS)
    _propagate_entry_held(model)
    findings: List[Finding] = []
    cycles = _order_cycles(model, findings)
    blocking = _blocking_findings(model, findings)
    guardians = _guardian_findings(model, findings)
    locks = len({
        lock for c in model.classes.values() for lock in c.locks.values()
    } | set(model.module_locks.values()))
    notes = [
        f"locks: {len(model.functions)} functions over "
        f"{len(model.classes)} classes, {locks} locks modeled; "
        f"{cycles} order cycle(s), {blocking} blocking-under-lock, "
        f"{guardians} guardian violation(s)"
    ]
    return findings, notes
