"""Ratcheted typing gate over the modules where a type error costs a verdict.

Targets (the data-path spine): ``fbas/``, ``encode/``,
``utils/telemetry.py``, ``backends/auto.py``.

Two engines, both driven by one ratchet file
(``tools/analyze/typing_ratchet.json``):

- **builtin** (always runs, zero dependencies): AST annotation coverage per
  module — the fraction of module/class-level function definitions whose
  return AND every parameter (``self``/``cls`` excluded, ``*args``/
  ``**kwargs`` included) carry annotations.  Nested defs (jit bodies, race
  workers, closures) are exempt: they are implementation detail whose types
  flow from the enclosing scope.  The ratchet records each module's
  coverage; a drop below the recorded value is a finding, and a NEW target
  module must enter at 1.0 — annotations can only accumulate.
- **mypy --strict** (runs when mypy is importable — CI installs it; the
  pinned container image does not carry it, which is exactly why the
  builtin floor exists): per-module error counts are compared against the
  ratchet's ``mypy_errors`` map.  A module with a recorded count may never
  exceed it; a module recorded at 0 is strict-clean forever.  Unrecorded
  modules are reported (not failed) with the command to ratchet them:
  ``python -m tools.analyze typing --update-ratchet``.

The ratchet only tightens on ``--update-ratchet`` when the measured value
IMPROVED; loosening it requires editing the JSON by hand in a reviewed
diff, which is the point.
"""

from __future__ import annotations

import ast
import json
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from tools.analyze.lint import Finding

RATCHET_PATH = Path(__file__).with_name("typing_ratchet.json")
RATCHET_SCHEMA = "qi-typing-ratchet/1"

TYPING_TARGETS = (
    "quorum_intersection_tpu/fbas",
    "quorum_intersection_tpu/encode",
    "quorum_intersection_tpu/utils/telemetry.py",
    "quorum_intersection_tpu/backends/auto.py",
    # ISSUE 4: the fault-injection registry and the crash-only checkpoint
    # writer join the spine — a type error in either costs exactly the
    # robustness they exist to provide.
    "quorum_intersection_tpu/utils/faults.py",
    "quorum_intersection_tpu/utils/checkpoint.py",
    # ISSUE 7: the certificate builder joins the spine — a type error in
    # the evidence/ledger assembly is exactly the kind of silent
    # unsoundness the independent checker exists to catch downstream.
    "quorum_intersection_tpu/cert.py",
    # ISSUE 9: the incremental re-analysis engine joins the spine — a
    # type confusion between SCC-local and global coordinates is exactly
    # the transplant unsoundness the fingerprint discipline exists to
    # prevent (fbas/diff.py rides the fbas directory target above).
    "quorum_intersection_tpu/delta.py",
    # ISSUE 11: the fleet front door and the serve transport seam join
    # the spine — a type error in routing/failover bookkeeping loses or
    # duplicates a request, and one in the wire shape breaks every
    # worker at once.
    "quorum_intersection_tpu/fleet.py",
    "quorum_intersection_tpu/serve_transport.py",
    # ISSUE 12: the typed query subsystem joins the spine — a type
    # confusion between the two families' coordinate spaces, or between
    # a masked variant and its base snapshot, is exactly the
    # wrong-answer-with-confidence failure the typed schema exists to
    # prevent.
    "quorum_intersection_tpu/query.py",
    # ISSUE 13: the serving engine and the pipeline entry join the spine
    # — the serve engine hands batches across threads (a type confusion
    # in its entry/ticket bookkeeping loses a request), and pipeline.py
    # is the one seam every backend, cert and batch path flows through.
    "quorum_intersection_tpu/serve.py",
    "quorum_intersection_tpu/pipeline.py",
    # ISSUE 18: the analyzer's own device-hygiene tier joins the spine —
    # the shared call graph and the two passes built on it (hot-path
    # hygiene, conservation proofs) gate every other module, so a type
    # confusion here silently weakens every gate downstream.
    "tools/analyze/callgraph.py",
    "tools/analyze/hygiene.py",
    "tools/analyze/conserve.py",
)


def target_files(root: Path) -> List[Path]:
    out: List[Path] = []
    for entry in TYPING_TARGETS:
        p = root / entry
        if p.is_file():
            out.append(p)
        elif p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
    return out


# ---------------------------------------------------------------------------
# builtin engine: annotation coverage


def _is_annotated(fn: ast.FunctionDef) -> bool:
    if fn.returns is None:
        return False
    a = fn.args
    params = [*a.posonlyargs, *a.args, *a.kwonlyargs]
    if params and params[0].arg in ("self", "cls"):
        params = params[1:]
    params += [p for p in (a.vararg, a.kwarg) if p is not None]
    return all(p.annotation is not None for p in params)


def annotation_coverage(path: Path) -> Tuple[float, int]:
    """``(coverage, total)`` over module/class-level defs (nested exempt)."""
    tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
    total = 0
    annotated = 0

    def scan(body: Sequence[ast.stmt]) -> None:
        nonlocal total, annotated
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                total += 1
                annotated += int(_is_annotated(node))
            elif isinstance(node, ast.ClassDef):
                scan(node.body)

    scan(tree.body)
    return (annotated / total if total else 1.0), total


# ---------------------------------------------------------------------------
# mypy engine


def run_mypy(root: Path) -> Optional[Dict[str, int]]:
    """Per-module strict error counts, or None when mypy is unavailable."""
    try:
        from mypy import api as mypy_api
    except ImportError:
        return None
    targets = [str(p) for p in target_files(root)]
    stdout, _, _ = mypy_api.run(
        ["--strict", "--no-error-summary", "--show-error-codes", *targets]
    )
    counts: Dict[str, int] = {t: 0 for t in targets}
    for line in stdout.splitlines():
        parts = line.split(":", 2)
        if len(parts) >= 3 and " error:" in line:
            counts[parts[0]] = counts.get(parts[0], 0) + 1
    return {
        str(Path(k).resolve().relative_to(root.resolve())): v
        for k, v in counts.items()
    }


# ---------------------------------------------------------------------------
# ratchet


def load_ratchet() -> Dict[str, object]:
    if RATCHET_PATH.exists():
        data = json.loads(RATCHET_PATH.read_text(encoding="utf-8"))
        if data.get("schema") == RATCHET_SCHEMA:
            return data
    return {"schema": RATCHET_SCHEMA, "annotation_coverage": {}, "mypy_errors": {}}


def run_typing_gate(root: Path, update_ratchet: bool = False) -> Tuple[List[Finding], List[str]]:
    """``(findings, notes)`` — notes are informational lines (skipped
    engines, unratcheted modules), never failures."""
    ratchet = load_ratchet()
    cov_ratchet: Dict[str, float] = dict(ratchet.get("annotation_coverage", {}))  # type: ignore[arg-type]
    mypy_ratchet: Dict[str, int] = dict(ratchet.get("mypy_errors", {}))  # type: ignore[arg-type]
    findings: List[Finding] = []
    notes: List[str] = []
    changed = False

    for path in target_files(root):
        rel = str(path.relative_to(root))
        coverage, total = annotation_coverage(path)
        recorded = cov_ratchet.get(rel)
        if recorded is None:
            if coverage < 1.0 and not update_ratchet:
                findings.append(Finding(
                    rule="typing-ratchet", path=rel, line=1,
                    message=(
                        f"new typing-gate module enters at full annotation "
                        f"coverage; measured {coverage:.2%} of {total} "
                        f"functions (annotate them, or record a baseline "
                        f"with --update-ratchet in a reviewed diff)"
                    ),
                ))
            cov_ratchet[rel] = round(coverage, 4)
            changed = True
        elif coverage < float(recorded) - 1e-9:
            findings.append(Finding(
                rule="typing-ratchet", path=rel, line=1,
                message=(
                    f"annotation coverage regressed: {coverage:.2%} < "
                    f"ratcheted {float(recorded):.2%} ({total} functions) — "
                    f"annotate the new/changed signatures"
                ),
            ))
        elif coverage > float(recorded) + 1e-9 and update_ratchet:
            cov_ratchet[rel] = round(coverage, 4)
            changed = True

    mypy_counts = run_mypy(root)
    if mypy_counts is None:
        notes.append(
            "mypy not importable in this environment; strict gate deferred "
            "to CI (the builtin annotation floor above still ran)"
        )
    else:
        for rel, count in sorted(mypy_counts.items()):
            recorded_n = mypy_ratchet.get(rel)
            if recorded_n is None:
                if count:
                    notes.append(
                        f"mypy --strict: {rel} has {count} errors "
                        f"(unratcheted; record with --update-ratchet)"
                    )
                if update_ratchet:
                    mypy_ratchet[rel] = count
                    changed = True
            elif count > int(recorded_n):
                findings.append(Finding(
                    rule="typing-ratchet", path=rel, line=1,
                    message=(
                        f"mypy --strict errors regressed: {count} > "
                        f"ratcheted {recorded_n}"
                    ),
                ))
            elif count < int(recorded_n) and update_ratchet:
                mypy_ratchet[rel] = count
                changed = True

    if update_ratchet and changed:
        RATCHET_PATH.write_text(
            json.dumps(
                {
                    "schema": RATCHET_SCHEMA,
                    "annotation_coverage": dict(sorted(cov_ratchet.items())),
                    "mypy_errors": dict(sorted(mypy_ratchet.items())),
                },
                indent=2,
            ) + "\n",
            encoding="utf-8",
        )
        notes.append(f"ratchet updated: {RATCHET_PATH}")

    return findings, notes
