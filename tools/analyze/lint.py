"""qi-lint: custom AST rules for this codebase's real failure modes.

Each rule is a function ``(ctx) -> Iterator[Finding]`` over one parsed
file; the catalog with per-rule rationale lives in docs/STATIC_ANALYSIS.md.
Suppress a single line with ``# qi-lint: allow(rule-name) — reason`` on the
flagged line or the line directly above it (multiple rules comma-separate);
the reason is mandatory by convention and review, not by the parser.

The scanner is pure ``ast`` — fixture files under test are never imported,
so a rule can be tested against deliberately-broken code (tests/
analyze_fixtures/) without that code ever running.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set

# ---------------------------------------------------------------------------
# findings + per-file context


@dataclass(frozen=True)
class Finding:
    """One rule violation at one source line."""

    rule: str
    path: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


_SUPPRESS_RE = re.compile(r"#\s*qi-lint:\s*allow\(([A-Za-z0-9_,\- ]+)\)")


class FileContext:
    """Parsed source + helpers shared by every rule."""

    def __init__(self, path: Path, rel: str, source: str) -> None:
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=str(path))
        self._parents: Dict[int, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self._parents[id(child)] = node

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_functions(self, node: ast.AST) -> List[ast.FunctionDef]:
        """Innermost-first chain of defs lexically containing ``node``."""
        return [
            a for a in self.ancestors(node)
            if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]

    def suppressed(self, rule: str, line: int) -> bool:
        for ln in (line, line - 1):
            if 1 <= ln <= len(self.lines):
                m = _SUPPRESS_RE.search(self.lines[ln - 1])
                if m and rule in {r.strip() for r in m.group(1).split(",")}:
                    return True
        return False

    def finding(self, rule: str, node: ast.AST, message: str) -> Iterator[Finding]:
        line = getattr(node, "lineno", 1)
        if not self.suppressed(rule, line):
            yield Finding(rule=rule, path=self.rel, line=line, message=message)


def _names_in(node: ast.AST) -> Set[str]:
    """Every bare identifier referenced anywhere under ``node``."""
    return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}


def _idents_in(node: ast.AST) -> Set[str]:
    """Names AND attribute components under ``node`` (for 'does anything in
    this scope mention a cancel token' style checks)."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
        elif isinstance(n, ast.arg):
            out.add(n.arg)
    return out


# ---------------------------------------------------------------------------
# telemetry call-site detection (shared with tools/analyze/surface.py)

# RunRecord emission methods → the surface kind they emit.
_TELEMETRY_METHODS: Dict[str, str] = {
    "add": "counter", "declare": "counter", "gauge": "gauge",
    "event": "event", "span": "span",
}


def _module_str_constants(ctx: FileContext) -> Dict[str, str]:
    """Module-level ``NAME = "literal"`` bindings (cached per context) —
    the one indirection the telemetry-name-literal rule allows."""
    cached = getattr(ctx, "_mod_str_consts", None)
    if cached is None:
        cached = {}
        for node in ctx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                cached[node.targets[0].id] = node.value.value
        ctx._mod_str_consts = cached  # type: ignore[attr-defined]
    return cached


def resolve_name_arg(ctx: FileContext, node: ast.AST) -> Optional[str]:
    """Resolve a telemetry/fault/env *name* argument statically: a string
    literal, a module-level string constant, or a dotted-prefix f-string
    (``f"phase.{x}"`` → ``"phase.*"`` — a sound wildcard for the surface
    inventory).  Anything else is ``None`` (unextractable — the
    telemetry-name-literal rule's finding)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return _module_str_constants(ctx).get(node.id)
    if isinstance(node, ast.JoinedStr) and node.values:
        if not any(isinstance(v, ast.FormattedValue) for v in node.values):
            # A placeholder-less f-string is just a literal — banking it
            # as a wildcard would let stale registry rows sharing the
            # prefix ride free past the drift gate.
            return "".join(
                v.value for v in node.values
                if isinstance(v, ast.Constant) and isinstance(v.value, str)
            ) or None
        first = node.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str) \
                and "." in first.value:
            return first.value + "*"
    return None


def resolve_name_args(ctx: FileContext, node: ast.AST) -> List[str]:
    """Like :func:`resolve_name_arg` but handles conditional expressions
    (``"a.hits" if hit else "a.misses"``) by resolving every branch —
    empty list means unextractable (the telemetry-name-literal finding)."""
    if isinstance(node, ast.IfExp):
        body = resolve_name_args(ctx, node.body)
        orelse = resolve_name_args(ctx, node.orelse)
        return body + orelse if body and orelse else []
    one = resolve_name_arg(ctx, node)
    return [one] if one is not None else []


def name_arg_expr(node: ast.Call) -> Optional[ast.AST]:
    """The *name* argument of an emission/fault/env call — positional
    first arg or the ``name=`` keyword (``rec.add(name="x")`` is legal
    and must not bypass extraction)."""
    if node.args:
        return node.args[0]
    for kw in node.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def _looks_like_record(ctx: FileContext, recv: ast.AST) -> bool:
    """Does this receiver expression denote the process-wide RunRecord —
    ``rec``/``record`` by convention, a ``get_run_record()`` call, a
    ``.rec``/``.record`` attribute, or ``self`` inside telemetry.py?"""
    if isinstance(recv, ast.Name):
        if recv.id in ("rec", "record"):
            return True
        return recv.id == "self" and ctx.rel.replace("\\", "/").endswith(
            "utils/telemetry.py")
    if isinstance(recv, ast.Attribute):
        return recv.attr in ("rec", "record", "_rec", "_record")
    if isinstance(recv, ast.Call):
        f = recv.func
        return (isinstance(f, ast.Name) and f.id == "get_run_record") or (
            isinstance(f, ast.Attribute) and f.attr == "get_run_record")
    return False


def telemetry_calls(ctx: FileContext) -> Iterator[tuple]:
    """``(kind, resolved_names_list, call_node)`` for every run-record
    emission call in the file — the one detector behind both the surface
    extraction and the telemetry-name-literal rule.  The names list is
    empty when the argument is not statically resolvable."""
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)):
            continue
        kind = _TELEMETRY_METHODS.get(node.func.attr)
        if kind is None or not _looks_like_record(ctx, node.func.value):
            continue
        arg = name_arg_expr(node)
        if arg is None:
            continue
        yield kind, resolve_name_args(ctx, arg), node


# ---------------------------------------------------------------------------
# rule: telemetry-name-literal


def rule_telemetry_name_literal(ctx: FileContext) -> Iterator[Finding]:
    """Telemetry and fault-point names must be statically resolvable —
    string literals, module-level constants, or dotted-prefix f-strings —
    so the qi-surface extraction (tools/analyze/surface.py) stays sound: a
    name built at runtime is invisible to the registry drift gate, which
    is exactly how an undocumented counter ships."""
    for kind, names, node in telemetry_calls(ctx):
        if not names:
            yield from ctx.finding(
                "telemetry-name-literal", node,
                f"{kind} name is not statically resolvable (use a string "
                f"literal, a module-level constant, or an f-string with a "
                f"dotted literal prefix) — qi-surface cannot extract it, "
                f"so the OBSERVABILITY registry gate cannot see it",
            )
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        fname = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if fname != "fault_point":
            continue
        arg = name_arg_expr(node)
        resolved = resolve_name_arg(ctx, arg) if arg is not None else None
        if resolved is None or resolved.endswith("*"):
            yield from ctx.finding(
                "telemetry-name-literal", node,
                "fault-point name is not a string literal or module-level "
                "constant — fault points are exact catalog keys (no "
                "wildcards), and qi-surface must see every firing site to "
                "prove the catalog has no dead entries",
            )


# ---------------------------------------------------------------------------
# rule: import-at-top

# Modules whose import cost is noise: lazy-importing them buys nothing and
# hides a file's dependencies.  Deliberately NOT here: jax, numpy, and
# everything under quorum_intersection_tpu — the repo's lazy-import
# discipline keeps jax (and backends that pull it) out of pure-CPU import
# paths, and that discipline must stay expressible.
CHEAP_STDLIB = frozenset({
    "abc", "argparse", "atexit", "collections", "contextlib", "dataclasses",
    "enum", "functools", "hashlib", "io", "itertools", "json", "logging",
    "math", "os", "pathlib", "re", "shutil", "struct", "subprocess", "sys",
    "tempfile", "textwrap", "threading", "time", "typing",
})


def rule_import_at_top(ctx: FileContext) -> Iterator[Finding]:
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if not ctx.enclosing_functions(node):
            continue  # module scope (or class body): fine
        if isinstance(node, ast.ImportFrom):
            roots = [(node.module or "").split(".")[0]] if node.level == 0 else []
        else:
            roots = [alias.name.split(".")[0] for alias in node.names]
        for root in roots:
            if root in CHEAP_STDLIB:
                yield from ctx.finding(
                    "import-at-top", node,
                    f"function-level import of cheap stdlib module {root!r}; "
                    f"move it to module scope (lazy imports are for jax/"
                    f"device/optional deps, not the standard library)",
                )
                break


# ---------------------------------------------------------------------------
# rule: no-bare-env-read


def _qi_literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str) \
            and node.value.startswith("QI_"):
        return node.value
    return None


def _is_environ(node: ast.AST) -> bool:
    """``os.environ`` or a bare ``environ``."""
    if isinstance(node, ast.Attribute) and node.attr == "environ":
        return True
    return isinstance(node, ast.Name) and node.id == "environ"


def rule_no_bare_env_read(ctx: FileContext) -> Iterator[Finding]:
    if ctx.rel.endswith("utils/env.py"):
        return  # the one module allowed to touch os.environ for QI_* keys
    for node in ast.walk(ctx.tree):
        key: Optional[str] = None
        if isinstance(node, ast.Call) and node.args:
            f = node.func
            # os.environ.get("QI_X") / environ.get("QI_X")
            if isinstance(f, ast.Attribute) and f.attr == "get" \
                    and _is_environ(f.value):
                key = _qi_literal(node.args[0])
            # os.getenv("QI_X")
            elif isinstance(f, ast.Attribute) and f.attr == "getenv":
                key = _qi_literal(node.args[0])
            elif isinstance(f, ast.Name) and f.id == "getenv":
                key = _qi_literal(node.args[0])
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load) \
                and _is_environ(node.value):
            key = _qi_literal(node.slice)
        if key is not None:
            yield from ctx.finding(
                "no-bare-env-read", node,
                f"bare read of {key}; route it through the registry "
                f"(quorum_intersection_tpu/utils/env.py qi_env/qi_env_flag) "
                f"so the documented catalog stays true",
            )


# ---------------------------------------------------------------------------
# rule: span-balance


def rule_span_balance(ctx: FileContext) -> Iterator[Finding]:
    """Every RunRecord span must be entered as a ``with`` context item: a
    span opened by hand (``sp = rec.span(...)`` + manual ``__enter__``) can
    miss its exit on an exception path, leaving the telemetry stream with a
    dangling enter — the imbalance this rule exists to make impossible."""
    with_items = {
        id(item.context_expr)
        for node in ast.walk(ctx.tree) if isinstance(node, ast.With)
        for item in node.items
    }
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "span"):
            continue
        recv = node.func.value
        looks_like_record = (
            (isinstance(recv, ast.Call)
             and ((isinstance(recv.func, ast.Name)
                   and recv.func.id == "get_run_record")
                  or (isinstance(recv.func, ast.Attribute)
                      and recv.func.attr == "get_run_record")))
            or (isinstance(recv, ast.Name) and recv.id in ("rec", "record"))
        )
        if looks_like_record and id(node) not in with_items:
            yield from ctx.finding(
                "span-balance", node,
                "RunRecord.span(...) used outside a `with` statement; a "
                "hand-opened span can leak its enter on an exception path — "
                "use `with rec.span(...) as sp:`",
            )


# ---------------------------------------------------------------------------
# rule: lock-discipline

# Attributes of lock-owning telemetry objects that must only mutate under
# their lock (RunRecord's counters/gauges/span+event lists and bookkeeping).
_GUARDED_ATTRS = frozenset({
    "counters", "gauges", "spans", "events", "dropped", "_sinks", "_next_id",
})
_MUTATING_METHODS = frozenset({
    "append", "setdefault", "update", "pop", "clear", "extend", "remove",
})


def _is_lock_expr(node: ast.AST) -> bool:
    """A with-item that acquires a lock: ``self._lock``, ``record._lock``,
    bare ``lock`` — any terminal identifier containing 'lock'."""
    if isinstance(node, ast.Attribute):
        return "lock" in node.attr.lower()
    if isinstance(node, ast.Name):
        return "lock" in node.id.lower()
    if isinstance(node, ast.Call):  # lock.acquire()-style helpers
        return _is_lock_expr(node.func)
    return False


def _lock_owning_classes(ctx: FileContext) -> Set[str]:
    """Classes that assign a ``*lock*`` attribute on self — only their
    guarded attrs are policed, so a dataclass that happens to have a field
    named ``events`` elsewhere stays out of scope."""
    owners: Set[str] = set()
    for cls in ast.walk(ctx.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        for node in ast.walk(cls):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Attribute) \
                            and isinstance(tgt.value, ast.Name) \
                            and tgt.value.id == "self" \
                            and "lock" in tgt.attr.lower():
                        owners.add(cls.name)
    return owners


def rule_lock_discipline(ctx: FileContext) -> Iterator[Finding]:
    owners = _lock_owning_classes(ctx)

    lock_depth_of: Dict[int, int] = {}

    def walk(node: ast.AST, depth: int) -> None:
        # Every node records the depth it sits at — including a With node
        # itself (its OWN acquisition counts only for its body), so a
        # lock-With nested in another lock-With sees depth > 0.
        lock_depth_of[id(node)] = depth
        if isinstance(node, ast.With):
            inner = depth + sum(
                1 for item in node.items if _is_lock_expr(item.context_expr)
            )
            for item in node.items:
                walk(item, depth)
            for child in node.body:
                walk(child, inner)
            return
        for child in ast.iter_child_nodes(node):
            walk(child, depth)

    walk(ctx.tree, 0)

    def depth(node: ast.AST) -> int:
        return lock_depth_of.get(id(node), 0)

    def in_lock_owner_method(node: ast.AST) -> bool:
        return any(
            isinstance(a, ast.ClassDef) and a.name in owners
            for a in ctx.ancestors(node)
        )

    def exempt(node: ast.AST) -> bool:
        fns = ctx.enclosing_functions(node)
        return bool(fns) and fns[0].name == "__init__"

    for node in ast.walk(ctx.tree):
        # (a) guarded-attr mutation outside the lock
        if owners:
            tgt_attr: Optional[ast.Attribute] = None
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) else [node.target]
                for tgt in targets:
                    if isinstance(tgt, ast.Subscript):
                        tgt = tgt.value
                    if isinstance(tgt, ast.Attribute) and tgt.attr in _GUARDED_ATTRS:
                        tgt_attr = tgt
            elif isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _MUTATING_METHODS \
                    and isinstance(node.func.value, ast.Attribute) \
                    and node.func.value.attr in _GUARDED_ATTRS:
                tgt_attr = node.func.value
            if tgt_attr is not None and in_lock_owner_method(node) \
                    and not exempt(node) and depth(node) == 0:
                yield from ctx.finding(
                    "lock-discipline", node,
                    f"mutation of guarded attribute {tgt_attr.attr!r} outside "
                    f"its lock; the race's threads mutate these concurrently "
                    f"— wrap in `with self._lock:`",
                )
        # (b) nested lock acquisition (lock-ordering hazard)
        if isinstance(node, ast.With):
            for item in node.items:
                if _is_lock_expr(item.context_expr) and depth(node) > 0:
                    yield from ctx.finding(
                        "lock-discipline", node,
                        "nested lock acquisition; the telemetry record and "
                        "its sinks each have their own lock — taking one "
                        "inside another invites lock-order inversion",
                    )
        # (c) sink emit under the record lock (emit takes the sink's lock)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("emit", "_emit") and depth(node) > 0:
            yield from ctx.finding(
                "lock-discipline", node,
                "sink emit while holding a lock; emit acquires the sink's "
                "own lock — copy the data out, release, then emit",
            )


# ---------------------------------------------------------------------------
# rule: cancel-token-plumbed

_THREAD_SPAWNERS = frozenset({"Thread", "_thread_factory"})
_CANCELLABLE_NATIVE = frozenset({"qi_check_scc_cancel"})


def rule_cancel_token_plumbed(ctx: FileContext) -> Iterator[Finding]:
    """A function that spawns a thread or enters the cancellable native
    search must have a CancelToken within lexical reach (a parameter,
    ``self.cancel``/``self._cancel``, or a token constructed in scope) —
    otherwise the racing auto router cannot stop the work it started, and a
    losing engine runs to completion on a thread nobody can reach."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else None
        )
        if name in _THREAD_SPAWNERS:
            what = "thread spawn"
        elif name in _CANCELLABLE_NATIVE:
            what = f"native call {name}"
        else:
            continue
        fns = ctx.enclosing_functions(node)
        scope: ast.AST = fns[-1] if fns else ctx.tree
        idents = _idents_in(scope)
        if not any("cancel" in ident.lower() for ident in idents):
            yield from ctx.finding(
                "cancel-token-plumbed", node,
                f"{what} with no CancelToken in reach; accept and forward a "
                f"`cancel` token (backends/base.CancelToken) so the race "
                f"driver can stop this work",
            )


# ---------------------------------------------------------------------------
# rule: degrade-via-ladder

# The one class allowed to catch Exception broadly in backends/: the auto
# router's explicit degradation ladder (ISSUE 4).  Handlers inside it are
# the sanctioned fall-through; everywhere else a broad catch must re-raise
# (typed), reference the ladder (it is reporting a transition), or carry a
# reviewed allow() with a reason.
_LADDER_CLASSES = frozenset({"DegradationLadder"})
_BROAD_EXC_NAMES = frozenset({"Exception", "BaseException"})


def _catches_broadly(handler: ast.ExceptHandler) -> bool:
    """Bare ``except:``, ``except Exception``/``BaseException``, or a tuple
    containing either."""
    t = handler.type
    if t is None:
        return True
    exprs = list(t.elts) if isinstance(t, ast.Tuple) else [t]
    for e in exprs:
        name = e.id if isinstance(e, ast.Name) else (
            e.attr if isinstance(e, ast.Attribute) else None
        )
        if name in _BROAD_EXC_NAMES:
            return True
    return False


def rule_degrade_via_ladder(ctx: FileContext) -> Iterator[Finding]:
    """Backend engines may not invent ad-hoc degradation policy: before the
    ladder, every ``except Exception: log-and-fall-through`` site was an
    untested failure path with its own (absent) retry/telemetry story — the
    exact erosion ISSUE 4 hardened away.  In ``backends/``, a broad catch
    must either re-raise (surfacing a typed error), run inside the
    DegradationLadder itself, or visibly report through it (a ``ladder``
    reference in the handler body).  Cleanup-only handlers carry an
    ``allow()`` with a reason, reviewed like any other suppression."""
    if "backends/" not in ctx.rel.replace("\\", "/"):
        return
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler) or not _catches_broadly(node):
            continue
        if any(
            isinstance(a, ast.ClassDef) and a.name in _LADDER_CLASSES
            for a in ctx.ancestors(node)
        ):
            continue
        if any(isinstance(n, ast.Raise) for n in ast.walk(node)):
            continue  # the failure is surfaced, not swallowed
        if any("ladder" in ident.lower() for ident in _idents_in(node)):
            continue  # the handler reports through the ladder API
        yield from ctx.finding(
            "degrade-via-ladder", node,
            "broad `except` that falls through without the ladder: route "
            "engine degradation through DegradationLadder.attempt (or "
            "record it via the ladder API) so every fallback is retried, "
            "bounded, and emits a `degrade` event — ad-hoc catch-and-"
            "continue sites are how the hardening erodes",
        )


# ---------------------------------------------------------------------------
# rule: jax-tracer-leak

_JIT_NAMES = frozenset({"jit"})
_TRACED_MODULES = frozenset({"jnp", "lax", "jax"})
_LAX_CONTROL_FLOW = frozenset({
    "while_loop", "fori_loop", "scan", "cond", "switch",
})


def _is_jit_expr(node: ast.AST) -> bool:
    """Does this decorator / callee expression denote jax.jit (possibly via
    functools.partial)?"""
    for n in ast.walk(node):
        if isinstance(n, ast.Attribute) and n.attr in _JIT_NAMES:
            return True
        if isinstance(n, ast.Name) and n.id in _JIT_NAMES:
            return True
    return False


def _traced_function_defs(ctx: FileContext) -> List[ast.FunctionDef]:
    """Functions whose bodies run under a jax trace: decorated with
    ``@jax.jit`` (or partial thereof), or referenced by name inside a
    ``jax.jit(...)`` call's arguments (``jax.jit(shard_map(fn, ...))``)."""
    defs: Dict[str, List[ast.FunctionDef]] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef):
            defs.setdefault(node.name, []).append(node)
    traced: List[ast.FunctionDef] = []
    seen: Set[int] = set()

    def mark(fn: ast.FunctionDef) -> None:
        if id(fn) not in seen:
            seen.add(id(fn))
            traced.append(fn)

    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.FunctionDef):
            if any(_is_jit_expr(d) for d in node.decorator_list):
                mark(node)
        elif isinstance(node, ast.Call) and _is_jit_expr(node.func):
            for arg in node.args:
                for name in _names_in(arg):
                    for fn in defs.get(name, []):
                        mark(fn)
    return traced


def _taint_flag_traced(
    ctx: FileContext, fn: ast.FunctionDef, inherited: Set[str]
) -> Iterator[Finding]:
    """Walk one traced function: taint its parameters plus anything derived
    from jnp/lax/jax expressions, flag Python control flow on tainted
    values, and recurse into nested callbacks handed to lax control flow."""
    a = fn.args
    taint: Set[str] = set(inherited)
    for arg in [*a.posonlyargs, *a.args, *a.kwonlyargs]:
        if arg.arg not in ("self", "cls"):
            taint.add(arg.arg)

    def expr_tainted(expr: ast.AST) -> bool:
        for n in ast.walk(expr):
            if isinstance(n, ast.Name) and n.id in taint:
                return True
            if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                    and n.value.id in _TRACED_MODULES:
                return True
        return False

    lax_callbacks: Set[str] = set()
    findings: List[Finding] = []

    def visit(node: ast.AST) -> None:
        if isinstance(node, ast.FunctionDef) and node is not fn:
            return  # nested defs handled separately below
        if isinstance(node, ast.Assign) and expr_tainted(node.value):
            for tgt in node.targets:
                taint.update(_names_in(tgt))
        elif isinstance(node, ast.AugAssign) and expr_tainted(node.value):
            taint.update(_names_in(node.target))
        elif isinstance(node, (ast.If, ast.While)) and expr_tainted(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            findings.extend(ctx.finding(
                "jax-tracer-leak", node,
                f"Python `{kind}` on a traced value inside a @jit region; "
                f"trace-time branching silently bakes one branch into the "
                f"compiled program (use lax.cond / jnp.where)",
            ))
        elif isinstance(node, ast.Assert) and expr_tainted(node.test):
            findings.extend(ctx.finding(
                "jax-tracer-leak", node,
                "Python `assert` on a traced value inside a @jit region; "
                "the tracer cannot be truth-tested at run time",
            ))
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id in ("bool", "int", "float") \
                    and node.args and expr_tainted(node.args[0]):
                findings.extend(ctx.finding(
                    "jax-tracer-leak", node,
                    f"`{f.id}()` on a traced value inside a @jit region "
                    f"forces concretization and fails under trace",
                ))
            if isinstance(f, ast.Attribute) and f.attr in _LAX_CONTROL_FLOW:
                for arg in node.args:
                    lax_callbacks.update(_names_in(arg))
        for child in ast.iter_child_nodes(node):
            visit(child)

    for stmt in fn.body:
        visit(stmt)
    yield from findings

    # Nested callbacks handed to lax control flow run traced with traced
    # arguments (loop carries): analyze them with their params tainted.
    for node in fn.body:
        for sub in ast.walk(node):
            if isinstance(sub, ast.FunctionDef) and sub is not fn \
                    and sub.name in lax_callbacks:
                yield from _taint_flag_traced(ctx, sub, taint)


def rule_jax_tracer_leak(ctx: FileContext) -> Iterator[Finding]:
    for fn in _traced_function_defs(ctx):
        yield from _taint_flag_traced(ctx, fn, set())


# ---------------------------------------------------------------------------
# driver

RULES = {
    "telemetry-name-literal": rule_telemetry_name_literal,
    "import-at-top": rule_import_at_top,
    "no-bare-env-read": rule_no_bare_env_read,
    "span-balance": rule_span_balance,
    "lock-discipline": rule_lock_discipline,
    "cancel-token-plumbed": rule_cancel_token_plumbed,
    "degrade-via-ladder": rule_degrade_via_ladder,
    "jax-tracer-leak": rule_jax_tracer_leak,
}

# What the repo-wide scan covers: the package, the tooling, and the bench
# drivers.  tests/ are deliberately out of scope — they monkeypatch, spawn
# bare threads, and read env vars as part of their job.
DEFAULT_SCAN = (
    "quorum_intersection_tpu",
    "tools",
    "bench.py",
    "benchmarks",
)


def iter_python_files(root: Path,
                      scan: Optional[Sequence[str]] = None) -> List[Path]:
    out: List[Path] = []
    for entry in scan if scan is not None else DEFAULT_SCAN:
        p = root / entry
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
    return out


def lint_file(path: Path, root: Optional[Path] = None,
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    rel = str(path.relative_to(root)) if root else str(path)
    try:
        source = path.read_text(encoding="utf-8")
        ctx = FileContext(path, rel, source)
    except (OSError, SyntaxError) as exc:
        return [Finding(rule="parse-error", path=rel, line=getattr(exc, "lineno", 1) or 1,
                        message=f"cannot parse: {exc}")]
    findings: List[Finding] = []
    for name in (rules or RULES):
        findings.extend(RULES[name](ctx))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def run_lint(root: Path, scan: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[str]] = None) -> List[Finding]:
    findings: List[Finding] = []
    for path in iter_python_files(root, scan):
        findings.extend(lint_file(path, root=root, rules=rules))
    return findings
