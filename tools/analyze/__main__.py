"""``python -m tools.analyze`` — run the static-analysis suite.

Usage::

    python -m tools.analyze                      # all: lint surface locks wire typing race hygiene conserve
    python -m tools.analyze lint typing          # a subset
    python -m tools.analyze --jsonl out.jsonl    # findings as qi-telemetry/1
    python -m tools.analyze typing --update-ratchet
    python -m tools.analyze surface --update-inventory

Exit status: 0 when every pass ran clean, 1 on any finding (2 on usage
errors).  ``--jsonl`` writes one ``qi-telemetry/1`` stream — a meta line,
one ``analyze.finding`` event per finding, and per-pass counters — so
``tools/metrics_report.py`` renders analyzer findings alongside run
records and CI can upload them as the same artifact family.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path
from typing import List, Optional

from tools.analyze.lint import Finding, run_lint
from tools.analyze.typing_gate import run_typing_gate

REPO_ROOT = Path(__file__).resolve().parent.parent.parent

PASSES = ("lint", "surface", "locks", "wire", "typing", "race", "hygiene",
          "conserve")


def _race_pass(root: Path) -> tuple:
    """``(findings, notes)``: forced-interleaving schedules (always) + a
    TSAN build-and-run of the native CLI (when the toolchain has the
    runtime — its absence is an environment note, not a finding; a
    *requested* sanitizer that cannot run fails loudly inside
    backends/cpp, which is the satellite's contract)."""
    findings: List[Finding] = []
    notes: List[str] = []

    from tools.analyze.schedules import ScheduleError, run_all

    try:
        results = run_all()
    except ScheduleError as exc:
        findings.append(Finding(
            rule="race-schedule", path="quorum_intersection_tpu/backends/auto.py",
            line=1, message=str(exc),
        ))
        results = []
    for r in results:
        if not r.ok:
            detail = (
                r.error if r.error is not None else
                f"produced verdict {r.verdict} (sequential chain says "
                f"{r.expected}; winner={r.winner})"
            )
            findings.append(Finding(
                rule="race-schedule",
                path="quorum_intersection_tpu/backends/auto.py", line=1,
                message=(
                    f"forced interleaving {r.schedule!r} on {r.topology}: "
                    f"{detail}"
                ),
            ))
    if results:
        notes.append(
            f"race schedules: {len(results)} forced interleavings, "
            f"verdicts identical to the sequential chain"
        )

    # Serving-layer schedules (ISSUE 8): the ServeEngine's drain thread +
    # deadline supervisor orderings, forced through serve._serve_sync the
    # same way the race orderings go through auto._race_sync.
    from tools.analyze.schedules import run_serve_schedules

    try:
        serve_results = run_serve_schedules()
    except ScheduleError as exc:
        findings.append(Finding(
            rule="race-schedule", path="quorum_intersection_tpu/serve.py",
            line=1, message=str(exc),
        ))
        serve_results = []
    for r in serve_results:
        if not r.ok:
            detail = (
                r.error if r.error is not None else
                f"produced verdict {r.verdict} (one-shot pipeline says "
                f"{r.expected})"
            )
            findings.append(Finding(
                rule="race-schedule",
                path="quorum_intersection_tpu/serve.py", line=1,
                message=(
                    f"forced interleaving {r.schedule!r} on {r.topology}: "
                    f"{detail}"
                ),
            ))
    if serve_results:
        notes.append(
            f"serve schedules: {len(serve_results)} forced interleavings, "
            f"typed errors + verdicts identical to the one-shot pipeline"
        )

    # qi-delta store schedules (ISSUE 9): the per-SCC verdict store's
    # single-flight lease orderings, forced through delta._delta_sync the
    # same way the serve orderings go through serve._serve_sync.
    from tools.analyze.schedules import run_delta_schedules

    try:
        delta_results = run_delta_schedules()
    except ScheduleError as exc:
        findings.append(Finding(
            rule="race-schedule", path="quorum_intersection_tpu/delta.py",
            line=1, message=str(exc),
        ))
        delta_results = []
    for r in delta_results:
        if not r.ok:
            detail = (
                r.error if r.error is not None else
                f"produced verdict {r.verdict} (one-shot pipeline says "
                f"{r.expected})"
            )
            findings.append(Finding(
                rule="race-schedule",
                path="quorum_intersection_tpu/delta.py", line=1,
                message=(
                    f"forced interleaving {r.schedule!r} on {r.topology}: "
                    f"{detail}"
                ),
            ))
    if delta_results:
        notes.append(
            f"delta schedules: {len(delta_results)} forced single-flight "
            f"interleavings, verdicts identical to the one-shot pipeline"
        )

    # qi-fleet schedules (ISSUE 11): the front door's routing/eviction/
    # replay orderings, forced through fleet._fleet_sync the same way the
    # delta orderings go through delta._delta_sync.
    from tools.analyze.schedules import run_fleet_schedules

    try:
        fleet_results = run_fleet_schedules()
    except ScheduleError as exc:
        findings.append(Finding(
            rule="race-schedule", path="quorum_intersection_tpu/fleet.py",
            line=1, message=str(exc),
        ))
        fleet_results = []
    for r in fleet_results:
        if not r.ok:
            detail = (
                r.error if r.error is not None else
                f"produced verdict {r.verdict} (one-shot pipeline says "
                f"{r.expected})"
            )
            findings.append(Finding(
                rule="race-schedule",
                path="quorum_intersection_tpu/fleet.py", line=1,
                message=(
                    f"forced interleaving {r.schedule!r} on {r.topology}: "
                    f"{detail}"
                ),
            ))
    if fleet_results:
        notes.append(
            f"fleet schedules: {len(fleet_results)} forced routing/failover "
            f"interleavings, exactly-once outcomes identical to the "
            f"one-shot pipeline"
        )

    # qi-fuse schedules (ISSUE 16): the cross-request batch former's
    # flush-vs-late-submit ordering, forced through fuse._fuse_sync the
    # same way the serve orderings go through serve._serve_sync.
    from tools.analyze.schedules import run_fuse_schedules

    try:
        fuse_results = run_fuse_schedules()
    except ScheduleError as exc:
        findings.append(Finding(
            rule="race-schedule", path="quorum_intersection_tpu/fuse.py",
            line=1, message=str(exc),
        ))
        fuse_results = []
    for r in fuse_results:
        if not r.ok:
            detail = (
                r.error if r.error is not None else
                f"produced verdict {r.verdict} (one-shot pipeline says "
                f"{r.expected})"
            )
            findings.append(Finding(
                rule="race-schedule",
                path="quorum_intersection_tpu/fuse.py", line=1,
                message=(
                    f"forced interleaving {r.schedule!r} on {r.topology}: "
                    f"{detail}"
                ),
            ))
    if fuse_results:
        notes.append(
            f"fuse schedules: {len(fuse_results)} forced flush-vs-submit "
            f"interleavings, verdicts identical to the one-shot pipeline"
        )

    # qi-cost schedules (ISSUE 17): the adaptive fuse-window controller's
    # decision-vs-late-admit ordering, forced through cost._cost_sync the
    # same way the fuse orderings go through fuse._fuse_sync.
    from tools.analyze.schedules import run_cost_schedules

    try:
        cost_results = run_cost_schedules()
    except ScheduleError as exc:
        findings.append(Finding(
            rule="race-schedule", path="quorum_intersection_tpu/cost.py",
            line=1, message=str(exc),
        ))
        cost_results = []
    for r in cost_results:
        if not r.ok:
            detail = (
                r.error if r.error is not None else
                f"produced verdict {r.verdict} (one-shot pipeline says "
                f"{r.expected})"
            )
            findings.append(Finding(
                rule="race-schedule",
                path="quorum_intersection_tpu/cost.py", line=1,
                message=(
                    f"forced interleaving {r.schedule!r} on {r.topology}: "
                    f"{detail}"
                ),
            ))
    if cost_results:
        notes.append(
            f"cost schedules: {len(cost_results)} forced window-decision "
            f"interleavings, verdicts identical to the one-shot pipeline"
        )

    from quorum_intersection_tpu.backends.cpp import build_native_cli

    try:
        tsan_cli = str(build_native_cli(sanitize="tsan"))
    except Exception as exc:  # noqa: BLE001 — toolchain-dependent
        notes.append(f"tsan build skipped: {exc}")
        return findings, notes
    tsan_findings_before = len(findings)
    for name, want_rc in (("trivial_correct.json", 0), ("trivial_broken.json", 1)):
        fixture = root / "fixtures" / name
        proc = subprocess.run(
            [tsan_cli], input=fixture.read_text(encoding="utf-8"),
            capture_output=True, text=True, timeout=300,
        )
        if "WARNING: ThreadSanitizer" in proc.stderr:
            findings.append(Finding(
                rule="tsan", path=f"fixtures/{name}", line=1,
                message="ThreadSanitizer report from the native CLI: "
                        + proc.stderr.splitlines()[0],
            ))
        elif proc.returncode != want_rc:
            findings.append(Finding(
                rule="tsan", path=f"fixtures/{name}", line=1,
                message=(
                    f"tsan-instrumented CLI exited {proc.returncode}, "
                    f"expected {want_rc}"
                ),
            ))
    if len(findings) == tsan_findings_before:
        notes.append(
            f"tsan native CLI clean on the trivial fixture pair ({tsan_cli})"
        )
    return findings, notes


def _emit_jsonl(path: str, per_pass: dict, t0: float) -> None:
    lines: List[dict] = [{
        "kind": "meta", "schema": "qi-telemetry/1", "pid": os.getpid(),
        "argv0": "tools.analyze", "t_wall": round(time.time(), 3),
    }]
    total = 0
    for pass_name, findings in per_pass.items():
        for f in findings:
            total += 1
            lines.append({
                "kind": "event", "name": "analyze.finding",
                "t_s": round(time.monotonic() - t0, 6), "span_id": None,
                "attrs": {
                    "pass": pass_name, "rule": f.rule, "file": f.path,
                    "line": f.line, "message": f.message,
                },
            })
        lines.append({
            "kind": "counter", "name": f"analyze.{pass_name}_findings",
            "value": len(findings),
        })
    lines.append({"kind": "counter", "name": "analyze.findings", "value": total})
    with open(path, "w", encoding="utf-8") as fh:
        for line in lines:
            fh.write(json.dumps(line, default=str) + "\n")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description=__doc__.splitlines()[0],
    )
    parser.add_argument(
        "passes", nargs="*", default=[], metavar="PASS",
        help=f"which passes to run (default: all of {', '.join(PASSES)})",
    )
    parser.add_argument(
        "--jsonl", metavar="PATH",
        help="write findings as a qi-telemetry/1 JSONL stream",
    )
    parser.add_argument(
        "--update-ratchet", action="store_true",
        help="record improved typing measurements into the ratchet file",
    )
    parser.add_argument(
        "--update-inventory", action="store_true",
        help="regenerate the committed qi-surface/1 inventory "
             "(tools/analyze/surface_inventory.json) from a fresh "
             "extraction — review the diff like any contract change",
    )
    args = parser.parse_args(argv)

    passes = args.passes or list(PASSES)
    for p in passes:
        if p not in PASSES:
            parser.error(f"unknown pass {p!r}; choose from {', '.join(PASSES)}")

    t0 = time.monotonic()
    per_pass: dict = {}
    notes: List[str] = []
    for pass_name in passes:
        if pass_name == "lint":
            per_pass["lint"] = run_lint(REPO_ROOT)
        elif pass_name == "surface":
            from tools.analyze.surface import run_surface

            findings, ns = run_surface(
                REPO_ROOT, update_inventory=args.update_inventory
            )
            per_pass["surface"] = findings
            notes.extend(ns)
        elif pass_name == "locks":
            from tools.analyze.locks import run_locks

            findings, ns = run_locks(REPO_ROOT)
            per_pass["locks"] = findings
            notes.extend(ns)
        elif pass_name == "wire":
            from tools.analyze.wire import run_wire

            findings, ns = run_wire(REPO_ROOT)
            per_pass["wire"] = findings
            notes.extend(ns)
        elif pass_name == "typing":
            findings, ns = run_typing_gate(
                REPO_ROOT, update_ratchet=args.update_ratchet
            )
            per_pass["typing"] = findings
            notes.extend(ns)
        elif pass_name == "race":
            findings, ns = _race_pass(REPO_ROOT)
            per_pass["race"] = findings
            notes.extend(ns)
        elif pass_name == "hygiene":
            from tools.analyze.hygiene import run_hygiene

            findings, ns = run_hygiene(REPO_ROOT)
            per_pass["hygiene"] = findings
            notes.extend(ns)
        elif pass_name == "conserve":
            from tools.analyze.conserve import run_conserve

            findings, ns = run_conserve(REPO_ROOT)
            per_pass["conserve"] = findings
            notes.extend(ns)

    total = 0
    for pass_name in passes:
        findings = per_pass[pass_name]
        total += len(findings)
        status = "clean" if not findings else f"{len(findings)} finding(s)"
        print(f"[analyze] pass {pass_name}: {status}")
        for f in findings:
            print(f"  {f.render()}")
    for note in notes:
        print(f"[analyze] note: {note}")

    if args.jsonl:
        _emit_jsonl(args.jsonl, per_pass, t0)
        print(f"[analyze] findings stream: {args.jsonl}")

    print(f"[analyze] {'CLEAN' if total == 0 else 'FAILED'} "
          f"({total} finding(s), {time.monotonic() - t0:.1f}s)")
    return 0 if total == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
