"""qi-conserve: exception-path conservation proofs for ledgers (pass 8).

The repo's load-bearing conservation invariants — the sweep ledger
partition (``enumerated + pruned + skipped + cancelled == 2^(|scc|-1)``),
qi-cost (``sum(attributed) + dropped == total``), the serve closure
(``requests == verdicts + errors``) — were until now enforced only
dynamically: a new early return or ``except`` arm that skips one counter
leg ships silently until a soak run catches it.  This pass proves the
counter *bookkeeping* statically.

:data:`CONSERVATION_TABLE` declares each invariant's **maintaining
region** (one function, resolved through the shared call graph) and its
**legs** in a frozen machine-parsed table (mirrored verbatim in
``docs/STATIC_ANALYSIS.md`` §Pass 8 — drift between code and docs is
itself a finding).  A CFG path enumeration then walks every exit path
of the region — normal completion, early ``return``, ``raise``, and
``except`` arms (handlers are entered with the *worst-case* prefix:
no body event yet) — and checks the declared obligation:

- ``paired`` mode: any path that books one leg of the invariant must
  book **every** leg group (conservation as co-occurrence: the path
  that bumps ``cert.windows_cancelled`` must also bump
  ``sweep.windows_cancelled``, or the operational plane silently
  drifts from the certificate ledger).
- ``exit`` mode: every exit path of the region (optionally filtered
  to ``return``/``raise`` exits) must book at least one leg from each
  group — e.g. every ``_resolve_*`` delivery books ``serve.verdicts``
  or ``serve.errors``.

Legs are counters (``serve.errors``), telemetry events
(``event:cost.degraded``), gauges (``gauge:slo.burning``) or calls
(``call:reuse_credit``); alternatives within a group separate with
``|``, groups with ``;``.  Violations report ``conserve-leg-missing``
with the offending exit path; a region that no longer books any
declared leg (or vanished) reports ``conserve-region-missing``.
Suppression uses the standard ``# qi-lint: allow(rule) — reason``.

The analysis is path-insensitive (infeasible branch combinations are
enumerated too), so obligations are declared on small, single-purpose
regions where every syntactic path is a real path.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from tools.analyze.callgraph import PackageGraph, build_graph
from tools.analyze.hygiene import default_targets
from tools.analyze.lint import (
    FileContext,
    Finding,
    _looks_like_record,
    resolve_name_arg,
)

DOC_PATH = "docs/STATIC_ANALYSIS.md"

# Paths kept per block before deterministic truncation.  Path sets are
# deduplicated by event content, so only event-carrying branch points
# multiply; the declared regions stay far below this.
PATH_CAP = 512

# (id, region "rel:qual", mode, exits, legs "group;group" with "|"
#  alternatives, law) — FROZEN: docs/STATIC_ANALYSIS.md §Pass 8 mirrors
# this table verbatim and the drift gate compares them field by field.
CONSERVATION_TABLE: Tuple[Tuple[str, str, str, str, str, str], ...] = (
    ("sweep-cancel-solo",
     "quorum_intersection_tpu/backends/tpu/sweep.py:TpuSweepBackend.check_scc.check_cancel",
     "paired", "all",
     "sweep.windows_cancelled;cert.windows_cancelled",
     "a cooperative cancel books the operational counter and the ledger twin together"),
    ("sweep-cancel-pack",
     "quorum_intersection_tpu/backends/tpu/sweep.py:TpuSweepBackend._run_pack.check_cancel",
     "paired", "all",
     "sweep.windows_cancelled;cert.windows_cancelled",
     "the packed drain's cancel books both twins like the unpacked drive"),
    ("sweep-retire-pack",
     "quorum_intersection_tpu/backends/tpu/sweep.py:TpuSweepBackend._run_pack.retire_job",
     "paired", "all",
     "sweep.windows_cancelled;cert.windows_cancelled",
     "a per-job retirement's unswept remainder books both cancel twins"),
    ("sweep-cost-solo",
     "quorum_intersection_tpu/backends/tpu/sweep.py:TpuSweepBackend.check_scc",
     "paired", "all",
     "cost.lane_windows_total;cost.lane_windows_attributed|cost.attribute_errors",
     "sum(attributed) + dropped == total: the total leg moves on every attribution path"),
    ("sweep-cost-pack",
     "quorum_intersection_tpu/backends/tpu/sweep.py:TpuSweepBackend._run_pack",
     "paired", "all",
     "cost.lane_windows_total;cost.lane_windows_attributed|cost.attribute_errors",
     "the pack twin of sweep-cost-solo"),
    ("serve-closure-ok",
     "quorum_intersection_tpu/serve.py:ServeEngine._resolve_ok",
     "exit", "all",
     "serve.verdicts|serve.errors",
     "requests == verdicts + errors: every delivery books exactly one closure leg"),
    ("serve-closure-deadline",
     "quorum_intersection_tpu/serve.py:ServeEngine._resolve_deadline",
     "exit", "all",
     "serve.deadline_expired;serve.errors",
     "an expired deadline is a typed error AND its own diagnostic counter"),
    ("serve-closure-err",
     "quorum_intersection_tpu/serve.py:ServeEngine._resolve_err",
     "exit", "all",
     "serve.errors",
     "a failed batch books one error per waiter — never a silent drop"),
    ("serve-admit-reject",
     "quorum_intersection_tpu/serve.py:ServeEngine._admit",
     "exit", "raise",
     "serve.errors",
     "every typed admission rejection counts toward requests == verdicts + errors"),
    ("cost-degrade-slo",
     "quorum_intersection_tpu/cost.py:SloPlane.evaluate",
     "paired", "all",
     "cost.attribute_errors;event:cost.degraded",
     "a degraded SLO evaluation bumps the error leg and emits the degrade event"),
    ("cost-degrade-fuse",
     "quorum_intersection_tpu/serve.py:ServeEngine._auto_fuse_window",
     "paired", "all",
     "cost.attribute_errors;event:cost.degraded",
     "a broken fusion controller degrades observably, never silently"),
    ("cost-degrade-respond",
     "quorum_intersection_tpu/serve.py:ServeEngine._resolve_ok",
     "paired", "all",
     "cost.attribute_errors;event:cost.degraded",
     "a dropped per-request cost attribution is counted and evented"),
    ("delta-compose",
     "quorum_intersection_tpu/delta.py:DeltaEngine._compose",
     "exit", "all",
     "call:reuse_credit|cost.attribute_errors",
     "every composed reuse credits its cost or routes through the cost.attribute degrade"),
    ("fleet-hedge",
     "quorum_intersection_tpu/fleet.py:FleetEngine._hedge_dispatch",
     "exit", "all",
     "fleet.hedges|fleet.hedge_errors",
     "a hedge decision is never silent: both legs sent, or the degrade leg booked"),
    ("fleet-ship",
     "quorum_intersection_tpu/fleet.py:FleetEngine._ship_journal",
     "exit", "all",
     "fleet.ships|fleet.ship_errors",
     "a cross-host journal ship resolves loudly: spooled + fsynced, or degraded to local-journal-only"),
    ("fleet-scale",
     "quorum_intersection_tpu/fleet.py:FleetEngine._apply_scale",
     "exit", "all",
     "fleet.scale_ups|fleet.scale_downs|fleet.scale_holds|fleet.scale_errors",
     "every elasticity tick books exactly one decision leg — a scale decision is never silent"),
)


def parse_legs(spec: str) -> Tuple[FrozenSet[str], ...]:
    """``"a;b|c"`` → ``(frozenset({a}), frozenset({b, c}))``."""
    return tuple(
        frozenset(alt.strip() for alt in group.split("|") if alt.strip())
        for group in spec.split(";") if group.strip()
    )


def render_table() -> str:
    """The frozen table as markdown — embedded in the docs and uploaded
    as the CI artifact next to the findings stream."""
    lines = [
        "| id | region | mode | exits | legs | law |",
        "| --- | --- | --- | --- | --- | --- |",
    ]
    for row_id, region, mode, exits, legs, law in CONSERVATION_TABLE:
        legs_md = legs.replace("|", "\\|")  # keep the markdown cell intact
        lines.append(
            f"| {row_id} | `{region}` | {mode} | {exits} | `{legs_md}` | {law} |")
    return "\n".join(lines) + "\n"


_DOC_ROW_RE = re.compile(
    r"^\|\s*(?P<id>[a-z0-9-]+)\s*\|\s*`(?P<region>[^`]+)`\s*\|"
    r"\s*(?P<mode>\w+)\s*\|\s*(?P<exits>\w+)\s*\|\s*`(?P<legs>[^`]+)`\s*\|"
)


def doc_table_rows(doc_text: str) -> List[Tuple[str, str, str, str, str]]:
    """Parse the docs mirror of the table (id, region, mode, exits, legs)."""
    rows: List[Tuple[str, str, str, str, str]] = []
    for line in doc_text.splitlines():
        m = _DOC_ROW_RE.match(line.strip())
        if m is not None:
            rows.append((m.group("id"), m.group("region"), m.group("mode"),
                         m.group("exits"),
                         m.group("legs").replace("\\|", "|")))
    return rows


# ---------------------------------------------------------------------------
# CFG path enumeration


class _Paths:
    """Event-set path bundles flowing out of a statement block."""

    def __init__(self, normal: Set[FrozenSet[str]],
                 brk: Optional[Set[FrozenSet[str]]] = None,
                 cont: Optional[Set[FrozenSet[str]]] = None) -> None:
        self.normal = normal
        self.brk = brk if brk is not None else set()
        self.cont = cont if cont is not None else set()


class _RegionWalker:
    """Enumerate exit paths of one region function as event sets."""

    def __init__(self, ctx: FileContext, fn_node: ast.AST) -> None:
        self.ctx = ctx
        self.fn_node = fn_node
        # (exit kind "return"|"raise", events, line)
        self.exits: List[Tuple[str, FrozenSet[str], int]] = []
        self.truncated = False

    def walk(self) -> None:
        body = list(getattr(self.fn_node, "body", []))
        out = self._seq(body, {frozenset()})
        last = body[-1].lineno if body else getattr(self.fn_node, "lineno", 1)
        for events in out.normal:
            self.exits.append(("return", events, last))

    # -- events --------------------------------------------------------------

    def _events(self, node: Optional[ast.AST]) -> FrozenSet[str]:
        if node is None:
            return frozenset()
        out: Set[str] = set()
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if isinstance(f, ast.Attribute):
                if f.attr in ("add", "event", "gauge") and sub.args \
                        and _looks_like_record(self.ctx, f.value):
                    name = resolve_name_arg(self.ctx, sub.args[0])
                    if name:
                        prefix = "" if f.attr == "add" else f"{f.attr}:"
                        out.add(f"{prefix}{name}")
                out.add(f"call:{f.attr}")
            elif isinstance(f, ast.Name):
                out.add(f"call:{f.id}")
        return frozenset(out)

    # -- path algebra --------------------------------------------------------

    def _cap(self, paths: Set[FrozenSet[str]]) -> Set[FrozenSet[str]]:
        if len(paths) <= PATH_CAP:
            return paths
        self.truncated = True
        ordered = sorted(paths, key=lambda p: (len(p), tuple(sorted(p))))
        return set(ordered[:PATH_CAP])

    def _extend(self, paths: Set[FrozenSet[str]],
                events: FrozenSet[str]) -> Set[FrozenSet[str]]:
        if not events:
            return paths
        return self._cap({p | events for p in paths})

    def _seq(self, stmts: Sequence[ast.stmt],
             entry: Set[FrozenSet[str]]) -> _Paths:
        cur = set(entry)
        brk: Set[FrozenSet[str]] = set()
        cont: Set[FrozenSet[str]] = set()
        for stmt in stmts:
            if not cur:
                break
            p = self._stmt(stmt, cur)
            brk |= p.brk
            cont |= p.cont
            cur = self._cap(p.normal)
        return _Paths(cur, brk, cont)

    def _stmt(self, stmt: ast.stmt, cur: Set[FrozenSet[str]]) -> _Paths:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return _Paths(cur)  # nested defs are their own regions
        if isinstance(stmt, ast.Return):
            events = self._events(stmt.value)
            for p in cur:
                self.exits.append(("return", p | events, stmt.lineno))
            return _Paths(set())
        if isinstance(stmt, ast.Raise):
            events = self._events(stmt.exc) | self._events(stmt.cause)
            for p in cur:
                self.exits.append(("raise", p | events, stmt.lineno))
            return _Paths(set())
        if isinstance(stmt, ast.Break):
            return _Paths(set(), brk=set(cur))
        if isinstance(stmt, ast.Continue):
            return _Paths(set(), cont=set(cur))
        if isinstance(stmt, ast.If):
            base = self._extend(cur, self._events(stmt.test))
            p_then = self._seq(stmt.body, base)
            p_else = self._seq(stmt.orelse, base)
            return _Paths(self._cap(p_then.normal | p_else.normal),
                          brk=p_then.brk | p_else.brk,
                          cont=p_then.cont | p_else.cont)
        if isinstance(stmt, (ast.For, ast.While)):
            head = stmt.iter if isinstance(stmt, ast.For) else stmt.test
            base = self._extend(cur, self._events(head))
            p_body = self._seq(stmt.body, base)
            # zero-or-one iteration: the after-loop set joins the skip
            # path, one full body pass, and any break/continue escape
            after = base | p_body.normal | p_body.brk | p_body.cont
            p_else = self._seq(stmt.orelse, self._cap(after))
            return _Paths(self._cap(p_else.normal))
        if isinstance(stmt, ast.With):
            events = frozenset().union(
                *(self._events(item.context_expr) for item in stmt.items)
            ) if stmt.items else frozenset()
            return self._seq(stmt.body, self._extend(cur, events))
        if isinstance(stmt, ast.Try):
            n_before = len(self.exits)
            p_body = self._seq(stmt.body, cur)
            normal = set(p_body.normal)
            brk = set(p_body.brk)
            cont = set(p_body.cont)
            for handler in stmt.handlers:
                # worst-case prefix: the exception fired before any body
                # event landed, so the handler starts from the try entry
                p_h = self._seq(handler.body, cur)
                normal |= p_h.normal
                brk |= p_h.brk
                cont |= p_h.cont
            p_else = self._seq(stmt.orelse, self._cap(normal)) \
                if stmt.orelse else _Paths(normal)
            normal = p_else.normal
            brk |= p_else.brk
            cont |= p_else.cont
            if stmt.finalbody:
                p_fin = self._seq(stmt.finalbody, {frozenset()})
                fin_sets = p_fin.normal or {frozenset()}
                # exits recorded inside the try ALSO run the finally
                for ix in range(n_before, len(self.exits)):
                    kind, events, line = self.exits[ix]
                    self.exits[ix] = (
                        kind, events | next(iter(sorted(
                            fin_sets, key=lambda s: tuple(sorted(s))))), line)
                normal = self._cap(
                    {n | f for n in normal for f in fin_sets})
                brk = self._cap({b | f for b in brk for f in fin_sets})
                cont = self._cap({c | f for c in cont for f in fin_sets})
            return _Paths(self._cap(normal), brk=brk, cont=cont)
        # plain statement: every embedded telemetry/call event lands
        return _Paths(self._extend(cur, self._events(stmt)))


# ---------------------------------------------------------------------------
# obligations


def _check_region(graph: PackageGraph, row: Tuple[str, str, str, str, str, str],
                  findings: List[Finding]) -> Tuple[int, int]:
    """Returns ``(leg_missing, region_missing)`` counts for one table row."""
    row_id, region, mode, exits, legs_spec, _law = row
    rel, qual = region.split(":", 1)
    key = (rel, qual)
    info = graph.infos.get(key)
    ctx = graph.ctxs.get(rel)
    if info is None or ctx is None:
        findings.append(Finding(
            rule="conserve-region-missing", path=rel, line=1,
            message=f"[{row_id}] maintaining region {qual} not found — the "
                    f"conservation table is stale or the region was "
                    f"renamed; update CONSERVATION_TABLE and the docs "
                    f"mirror together"))
        return 0, 1
    groups = parse_legs(legs_spec)
    all_legs = frozenset().union(*groups)
    walker = _RegionWalker(ctx, info.node)
    walker.walk()
    leg_missing = 0
    region_missing = 0
    maintained = False
    reported: Set[Tuple[int, str]] = set()
    for kind, events, line in walker.exits:
        if exits != "all" and kind != exits:
            continue
        if mode == "paired" and not (events & all_legs):
            continue
        if all(events & g for g in groups):
            maintained = True
            continue
        missing = [sorted(g) for g in groups if not (events & g)]
        booked = sorted(events & all_legs)
        mark = (line, ",".join("|".join(m) for m in missing))
        if mark in reported or ctx.suppressed("conserve-leg-missing", line):
            continue
        reported.add(mark)
        leg_missing += 1
        findings.append(Finding(
            rule="conserve-leg-missing", path=rel, line=line,
            message=f"[{row_id}] {kind} path out of {qual} books "
                    f"{booked or ['no declared leg']} but not "
                    f"{' nor '.join('|'.join(m) for m in missing)} — every "
                    f"{exits if exits != 'all' else 'exit'} path must "
                    f"update all legs of the invariant (or route through "
                    f"its declared degrade leg)"))
    if mode == "paired" and not maintained and leg_missing == 0:
        line = getattr(info.node, "lineno", 1)
        if not ctx.suppressed("conserve-region-missing", line):
            region_missing += 1
            findings.append(Finding(
                rule="conserve-region-missing", path=rel, line=line,
                message=f"[{row_id}] no path through {qual} books the "
                        f"declared legs ({legs_spec}) — the invariant is "
                        f"no longer maintained here; fix the region or "
                        f"update the table (docs mirror included)"))
    return leg_missing, region_missing


def _check_doc_mirror(root: Path, findings: List[Finding]) -> int:
    doc = root / DOC_PATH
    expected = [(r[0], r[1], r[2], r[3], r[4]) for r in CONSERVATION_TABLE]
    try:
        got = doc_table_rows(doc.read_text(encoding="utf-8"))
    except OSError:
        got = []
    if got == expected:
        return 0
    findings.append(Finding(
        rule="conserve-table-drift", path=DOC_PATH, line=1,
        message="the conservation table in docs/STATIC_ANALYSIS.md §Pass 8 "
                "does not match tools/analyze/conserve.py "
                "CONSERVATION_TABLE — regenerate the docs mirror with "
                "`python -m tools.analyze.conserve --dump-table` and paste "
                "it verbatim (the table is frozen: code and docs move "
                "together)"))
    return 1


def run_conserve(root: Path, targets: Optional[Sequence[str]] = None,
                 table: Optional[Sequence[Tuple[str, str, str, str, str, str]]]
                 = None, check_docs: bool = True,
                 ) -> Tuple[List[Finding], List[str]]:
    """``(findings, notes)`` — the conservation-proof pass."""
    rels = list(targets) if targets is not None else default_targets(root)
    rows = tuple(table) if table is not None else CONSERVATION_TABLE
    graph = build_graph(root, rels)
    findings: List[Finding] = []
    legs = 0
    regions = 0
    for row in rows:
        lm, rm = _check_region(graph, row, findings)
        legs += lm
        regions += rm
    drift = _check_doc_mirror(root, findings) if check_docs else 0
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    notes = [
        f"conserve: {len(rows)} obligation(s) over "
        f"{len({r[1] for r in rows})} region(s); "
        f"{legs} leg-missing, {regions} region-missing, "
        f"{drift} table-drift"
    ]
    return findings, notes


if __name__ == "__main__":  # pragma: no cover — tiny CI artifact helper
    import argparse

    ap = argparse.ArgumentParser(
        description="conservation-table tooling (the pass itself runs "
                    "under `python -m tools.analyze`)")
    ap.add_argument("--dump-table", metavar="FILE", default=None,
                    help="write the frozen table as markdown (CI artifact; "
                         "'-' for stdout)")
    ns = ap.parse_args()
    if ns.dump_table:
        text = render_table()
        if ns.dump_table == "-":
            print(text, end="")
        else:
            Path(ns.dump_table).write_text(text, encoding="utf-8")
