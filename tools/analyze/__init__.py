"""qi-analyze: the repo-native static-analysis suite (ISSUE 3 tentpole).

One entry point — ``python -m tools.analyze`` — runs three passes and exits
nonzero on any finding:

- **lint** (:mod:`tools.analyze.lint`): custom AST rules tuned to this
  codebase's real failure modes (tracer leaks in jit regions, unbalanced
  telemetry spans, counters mutated outside their lock, thread spawns
  without a CancelToken in reach, bare ``QI_*`` env reads, lazy imports of
  cheap stdlib modules);
- **typing** (:mod:`tools.analyze.typing_gate`): a ratcheted annotation
  gate over ``fbas/``, ``encode/``, ``utils/telemetry.py`` and
  ``backends/auto.py`` — strict mypy when the toolchain has it, a built-in
  AST annotation-coverage floor always;
- **race** (:mod:`tools.analyze.schedules`): the deterministic-interleaving
  harness that forces the auto-router race through its nasty orderings
  instead of hoping the wall clock finds them, plus a
  ``-fsanitize=thread`` build-and-run of the native CLI when the toolchain
  carries the TSAN runtime.

Why a repo-native tool instead of off-the-shelf linters: the bugs that
matter here do not crash — the quorum-intersection decision is NP-hard, so
a mis-routed solve or a silently-flipped verdict hides behind timeouts and
budget burns.  The rules below are machine-checked statements of THIS
repo's invariants (docs/STATIC_ANALYSIS.md catalogs each with its
rationale and suppression syntax).
"""

from tools.analyze.lint import Finding, run_lint  # noqa: F401

__all__ = ["Finding", "run_lint"]
