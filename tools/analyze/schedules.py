"""Deterministic-interleaving harness for the auto-router race (ISSUE 3).

The race in ``backends/auto.py`` has exactly three nasty orderings the wall
clock almost never produces on a laptop but production traffic will:

- **sweep-wins-then-oracle-finishes** — the sweep's verdict lands first,
  its cancel reaches the oracle too late, and BOTH engines finish.  The
  driver must prefer the oracle's result (witness-stable vs the sequential
  path) and still report a coherent race record.
- **cancel-during-compile** — the oracle wins while the sweep worker is
  inside its compile/spin-up phase; the cancel must be observed there (not
  just in the window loop) and the worker must unwind without recording
  progress.
- **both-finish-simultaneously** — the sweep's verdict is recorded but its
  cancel has not fired when the oracle's own verdict completes; the driver
  sees two finished engines in the same scheduling quantum.

Instead of sleeping and hoping, this harness monkeypatches the
``_race_sync`` hook ``backends/auto.py`` exposes and gates the fake
engines on the hook's *reached* events, so each ordering is FORCED, every
run, in milliseconds.  Verdicts are delegated to the real Python oracle so
they are real; the invariant checked is the ISSUE 3 acceptance criterion —
**identical verdicts under every interleaving**, equal to the sequential
(``race=False``) chain's verdict.

Used by ``python -m tools.analyze`` (race pass) and
``tests/test_race_schedules.py``.
"""

from __future__ import annotations

import tempfile
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional

# Bounded waits everywhere: a schedule that deadlocks fails loudly with the
# point name instead of hanging the analyze run or the test suite.
WAIT_S = 30.0


class ScheduleError(AssertionError):
    """A forced interleaving did not complete (gate timeout / wrong path)."""


class SyncController:
    """Replacement for ``backends.auto._race_sync``.

    Records every point the race reaches (``reached[point]`` is set the
    moment any thread passes it) and optionally *holds* a point until
    another event fires — the mechanism that serializes the two race
    threads into the exact ordering a schedule wants.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.reached: Dict[str, threading.Event] = {}
        self._holds: Dict[str, threading.Event] = {}
        self.trace: List[str] = []

    def reached_event(self, point: str) -> threading.Event:
        with self._lock:
            return self.reached.setdefault(point, threading.Event())

    def hold(self, point: str, until: threading.Event) -> None:
        """Block any thread passing ``point`` until ``until`` fires."""
        with self._lock:
            self._holds[point] = until

    def __call__(self, point: str) -> None:
        with self._lock:
            self.trace.append(point)
            gate = self._holds.get(point)
        self.reached_event(point).set()
        if gate is not None and not gate.wait(WAIT_S):
            raise ScheduleError(f"sync point {point!r} held past {WAIT_S}s")


class FakeOracle:
    """Host-oracle stand-in: real verdict (delegated to the Python oracle),
    scheduled lifecycle.  ``wait_for`` delays the verdict; ``ignore_cancel``
    models a cancel that lands after the search already finished;
    ``burn_budget`` raises OracleBudgetExceeded instead of answering."""

    name = "cpp"

    def __init__(self, cancel=None, wait_for: Optional[threading.Event] = None,
                 ignore_cancel: bool = False, burn_budget: bool = False) -> None:
        self.cancel = cancel
        self.wait_for = wait_for
        self.ignore_cancel = ignore_cancel
        self.burn_budget = burn_budget

    def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
        from quorum_intersection_tpu.backends.base import (
            OracleBudgetExceeded,
            SearchCancelled,
        )
        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend,
        )

        if self.wait_for is not None and not self.wait_for.wait(WAIT_S):
            raise ScheduleError("oracle gate never released")
        if self.burn_budget:
            raise OracleBudgetExceeded("scheduled budget burn")
        if (not self.ignore_cancel and self.cancel is not None
                and self.cancel.cancelled):
            raise SearchCancelled("scheduled oracle cancel")
        res = PythonOracleBackend().check_scc(
            graph, circuit, scc, scope_to_scc=scope_to_scc
        )
        res.stats["backend"] = self.name
        return res


class FakeSweep:
    """Sweep stand-in with an explicit compile phase.

    ``compiling`` is set when the engine enters its (fake) spin-up;
    ``cancel_in_compile=True`` parks it there until the cancel token fires
    — the cancel-during-compile ordering — and raises SearchCancelled, the
    real sweep's pre-dispatch cancel behavior.  Otherwise the engine
    produces a real verdict (optionally after ``wait_for``)."""

    name = "tpu-sweep"

    def __init__(self, cancel=None, compiling: Optional[threading.Event] = None,
                 cancel_in_compile: bool = False,
                 wait_for: Optional[threading.Event] = None) -> None:
        self.cancel = cancel
        self.compiling = compiling
        self.cancel_in_compile = cancel_in_compile
        self.wait_for = wait_for

    def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
        from quorum_intersection_tpu.backends.base import SearchCancelled
        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend,
        )

        if self.compiling is not None:
            self.compiling.set()
        if self.cancel_in_compile:
            assert self.cancel is not None
            if not self.cancel._event.wait(WAIT_S):
                raise ScheduleError("sweep was never cancelled in compile")
            raise SearchCancelled("sweep cancelled during compile")
        if self.wait_for is not None and not self.wait_for.wait(WAIT_S):
            raise ScheduleError("sweep gate never released")
        if self.cancel is not None and self.cancel.cancelled:
            raise SearchCancelled("sweep observed cancel before verdict")
        res = PythonOracleBackend().check_scc(
            graph, circuit, scc, scope_to_scc=scope_to_scc
        )
        res.stats["backend"] = self.name
        return res


@dataclass
class ScheduleResult:
    schedule: str
    topology: str
    verdict: bool
    expected: bool
    winner: str
    oracle_outcome: str
    trace: List[str] = field(default_factory=list)
    # Non-None when the interleaving did not actually happen: the worker
    # errored (auto.py's sweep_worker swallows engine exceptions into
    # outcome["sweep_error"] — including a ScheduleError from a timed-out
    # gate), or a sync point the ordering is DEFINED by never fired.  A
    # matching verdict with a broken ordering must not report clean: the
    # whole point of the harness is that the ordering was exercised.
    error: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.error is None and self.verdict == self.expected


def _run_one(schedule: str, data: object, expected: bool,
             topology: str) -> ScheduleResult:
    import quorum_intersection_tpu.backends.auto as auto_mod
    from quorum_intersection_tpu.backends.auto import AutoBackend
    from quorum_intersection_tpu.pipeline import solve

    ctl = SyncController()

    if schedule == "sweep_wins_then_oracle_finishes":
        # Sweep answers immediately; oracle waits until the sweep's verdict
        # is recorded, then finishes anyway (its cancel arrives mid-flight
        # and is deliberately ignored — too late to matter).
        def make_oracle(self, budget_s=None, cancel=None):
            return FakeOracle(
                cancel=cancel,
                wait_for=ctl.reached_event("sweep.verdict"),
                ignore_cancel=True,
            )

        def make_sweep(self, cancel=None, engine=None):
            return FakeSweep(cancel=cancel)

    elif schedule == "cancel_during_compile":
        # Oracle answers the moment the sweep has entered its compile
        # phase; the sweep parks in compile until the cancel lands.
        compiling = threading.Event()

        def make_oracle(self, budget_s=None, cancel=None):
            return FakeOracle(cancel=cancel, wait_for=compiling)

        def make_sweep(self, cancel=None, engine=None):
            return FakeSweep(
                cancel=cancel, compiling=compiling, cancel_in_compile=True
            )

    elif schedule == "both_finish_simultaneously":
        # Both engines produce verdicts; the worker is HELD between
        # recording its result and firing the oracle's cancel until the
        # oracle's own verdict has completed — the driver then sees two
        # finished engines at once.
        ctl.hold("sweep.verdict", ctl.reached_event("oracle.returned"))

        def make_oracle(self, budget_s=None, cancel=None):
            return FakeOracle(
                cancel=cancel,
                wait_for=ctl.reached_event("sweep.started"),
                ignore_cancel=True,
            )

        def make_sweep(self, cancel=None, engine=None):
            return FakeSweep(cancel=cancel)

    elif schedule == "budget_burn_then_sweep_verdict":
        # The sequential fallback ordering, forced: the oracle burns its
        # budget first, the already-spinning sweep then delivers.
        def make_oracle(self, budget_s=None, cancel=None):
            return FakeOracle(cancel=cancel, burn_budget=True)

        def make_sweep(self, cancel=None, engine=None):
            return FakeSweep(
                cancel=cancel, wait_for=ctl.reached_event("oracle.returned")
            )

    else:
        raise ValueError(f"unknown schedule {schedule!r}")

    class ScheduledAuto(AutoBackend):
        _cpu_oracle = make_oracle
        _sweep = make_sweep

    old_sync = auto_mod._race_sync
    auto_mod._race_sync = ctl
    try:
        res = solve(data, backend=ScheduledAuto())
    finally:
        auto_mod._race_sync = old_sync

    race = res.stats.get("race", {})
    error: Optional[str] = None
    for key in ("sweep_error", "sweep_ineligible"):
        if key in race:
            error = f"{key}: {race[key]}"
    missing = [p for p in _REQUIRED_POINTS[schedule] if p not in ctl.trace]
    if error is None and missing:
        error = f"ordering never happened: sync point(s) {missing} not reached"
    return ScheduleResult(
        schedule=schedule,
        topology=topology,
        verdict=res.intersects,
        expected=expected,
        winner=str(race.get("winner", "?")),
        oracle_outcome=str(race.get("oracle_outcome", "?")),
        trace=list(ctl.trace),
        error=error,
    )


SCHEDULES = (
    "sweep_wins_then_oracle_finishes",
    "cancel_during_compile",
    "both_finish_simultaneously",
    "budget_burn_then_sweep_verdict",
)

# The sync points each ordering is DEFINED by: absent from the trace, the
# schedule degenerated (a gate timed out, an engine errored and auto.py's
# degrade path hid it) and must be reported broken even if the verdict
# happens to match.
_REQUIRED_POINTS: Dict[str, tuple] = {
    "sweep_wins_then_oracle_finishes": ("sweep.verdict", "oracle.returned"),
    "cancel_during_compile": ("oracle.returned", "sweep.unwound"),
    "both_finish_simultaneously": ("sweep.verdict", "oracle.returned"),
    "budget_burn_then_sweep_verdict": ("oracle.returned", "sweep.verdict"),
}


# ---- serving-layer schedules (ISSUE 8) --------------------------------------
#
# The ServeEngine's drain thread + deadline timers introduce a second
# concurrency surface with its own nasty orderings; ``serve._serve_sync``
# is the hook, exactly like ``auto._race_sync`` above.

SERVE_SCHEDULES = (
    "serve_coalesce_during_solve",
    "serve_deadline_between_pop_and_solve",
    "serve_shed_while_drain_parked",
)

_REQUIRED_SERVE_POINTS: Dict[str, tuple] = {
    # coalesce: the second submit must have taken the single-flight path
    # WHILE the entry was popped-but-unsolved (drain parked at the point).
    "serve_coalesce_during_solve": ("drain.popped", "admit.coalesced"),
    # deadline: the drain must have popped before the expiry was handled.
    "serve_deadline_between_pop_and_solve": ("drain.popped",),
    # shed: a queue at its bound while the drain is parked mid-cycle.
    "serve_shed_while_drain_parked": ("drain.popped", "admit.queued"),
}


def _run_serve_one(schedule: str, data: object, expected: bool,
                   topology: str) -> ScheduleResult:
    import quorum_intersection_tpu.serve as serve_mod
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.serve import (
        DeadlineExceeded,
        Overloaded,
        ServeEngine,
    )

    ctl = SyncController()
    release = threading.Event()
    verdict: Optional[bool] = None
    error: Optional[str] = None
    old_sync = serve_mod._serve_sync
    serve_mod._serve_sync = ctl
    engine: Optional[ServeEngine] = None
    try:
        if schedule == "serve_coalesce_during_solve":
            # The drain pops the entry, then parks BEFORE solving; an
            # identical submit lands meanwhile and must coalesce onto the
            # in-flight entry (single-flight), not re-queue a second solve.
            ctl.hold("drain.popped", ctl.reached_event("admit.coalesced"))
            engine = ServeEngine(backend="python")
            engine.start()
            t1 = engine.submit(data)
            if not ctl.reached_event("drain.popped").wait(WAIT_S):
                raise ScheduleError("drain never popped the entry")
            t2 = engine.submit(data)
            r1, r2 = t1.result(WAIT_S), t2.result(WAIT_S)
            verdict = r1.intersects
            if r2.intersects is not r1.intersects:
                error = (
                    f"coalesced waiter verdict {r2.intersects} != "
                    f"primary {r1.intersects}"
                )
        elif schedule == "serve_deadline_between_pop_and_solve":
            # The request's deadline expires in the gap between queue pop
            # and solve: the engine must deliver a typed DeadlineExceeded
            # (never a wedge, never a late verdict pretending to be timely)
            # and keep serving afterwards.
            ctl.hold("drain.popped", release)
            engine = ServeEngine(backend="python")
            engine.start()
            t1 = engine.submit(data, deadline_s=0.05)
            if not ctl.reached_event("drain.popped").wait(WAIT_S):
                raise ScheduleError("drain never popped the entry")
            assert t1.deadline_t is not None
            while time.monotonic() < t1.deadline_t:
                time.sleep(0.005)
            release.set()
            try:
                t1.result(WAIT_S)
                error = "expired request was served instead of raising"
            except DeadlineExceeded:
                pass
            t2 = engine.submit(data)  # the engine must not be wedged
            verdict = t2.result(WAIT_S).intersects
        elif schedule == "serve_shed_while_drain_parked":
            # Queue bound 1, drain parked mid-cycle: the second distinct
            # request fills the queue, the third must shed with a typed
            # Overloaded — and both admitted requests must still serve.
            ctl.hold("drain.popped", release)
            engine = ServeEngine(backend="python", queue_depth=1)
            engine.start()
            t_a = engine.submit(data)
            if not ctl.reached_event("drain.popped").wait(WAIT_S):
                raise ScheduleError("drain never popped the entry")
            t_b = engine.submit(majority_fbas(5, prefix="SHED"))
            try:
                engine.submit(majority_fbas(7, prefix="SHED"))
                error = "over-depth request admitted instead of shed"
            except Overloaded:
                pass
            release.set()
            r_a = t_a.result(WAIT_S)
            t_b.result(WAIT_S)  # must deliver, verdict checked vs its own solve
            verdict = r_a.intersects
        else:
            raise ValueError(f"unknown serve schedule {schedule!r}")
    finally:
        serve_mod._serve_sync = old_sync
        release.set()
        if engine is not None:
            engine.stop(drain=True, timeout=WAIT_S)
    missing = [
        p for p in _REQUIRED_SERVE_POINTS[schedule] if p not in ctl.trace
    ]
    if error is None and missing:
        error = f"ordering never happened: sync point(s) {missing} not reached"
    return ScheduleResult(
        schedule=schedule,
        topology=topology,
        verdict=bool(verdict),
        expected=expected,
        winner="serve",
        oracle_outcome="-",
        trace=list(ctl.trace),
        error=error,
    )


def run_serve_schedules(join_timeout: float = 5.0) -> List[ScheduleResult]:
    """Every serve schedule × {intersecting, broken} topology; ground truth
    from the one-shot pipeline (the differential contract the serving layer
    is held to everywhere).  Leaked drain threads are a failure."""
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    results: List[ScheduleResult] = []
    for broken in (False, True):
        data = majority_fbas(9, broken=broken)
        topology = "majority9-broken" if broken else "majority9"
        expected = solve(data, backend="python").intersects
        for schedule in SERVE_SCHEDULES:
            results.append(_run_serve_one(schedule, data, expected, topology))
    leaked = [
        t for t in threading.enumerate() if t.name == "qi-serve-drain"
    ]
    for t in leaked:
        t.join(timeout=join_timeout)
    leaked = [t for t in leaked if t.is_alive()]
    if leaked:
        raise ScheduleError(
            f"{len(leaked)} serve drain thread(s) still alive after "
            f"{join_timeout}s — a schedule leaked its engine"
        )
    return results


# ---- qi-delta store schedules (ISSUE 9) -------------------------------------
#
# The per-SCC verdict store's single-flight lease (delta.py
# SccVerdictStore.lease_verdict) has two orderings worth forcing: a
# follower must actually WAIT while a leader solves (one backend call for
# two concurrent identical snapshots), and a follower whose leader FAILS
# must take the lease over and still produce the verdict.
# ``delta._delta_sync`` is the hook, exactly like ``serve._serve_sync``.

DELTA_SCHEDULES = (
    "delta_follower_waits_for_leader",
    "delta_leader_fails_follower_takes_over",
)

_REQUIRED_DELTA_POINTS: Dict[str, tuple] = {
    # the follower must have parked on the leader's lease (store.wait)
    # before the leader published — single-flight actually happened.
    "delta_follower_waits_for_leader": (
        "store.leader", "store.wait", "store.publish",
    ),
    # the failed leader must have published its failed lease (waking the
    # follower to re-take it — a second store.leader after wait) BEFORE
    # degrading to its own full re-solve.
    "delta_leader_fails_follower_takes_over": (
        "store.wait", "store.publish", "store.leader",
    ),
}


class _CountingOracle:
    """Python-oracle delegate that counts (and optionally fails) solves —
    the observable the single-flight schedules pin."""

    name = "python"
    needs_circuit = False

    def __init__(self, fail_first: bool = False) -> None:
        self.calls = 0
        self.fail_first = fail_first
        self._count_lock = threading.Lock()

    def check_scc(self, graph, circuit, scc, *, scope_to_scc=False):
        from quorum_intersection_tpu.backends.python_oracle import (
            PythonOracleBackend,
        )

        with self._count_lock:
            self.calls += 1
            n = self.calls
        if self.fail_first and n == 1:
            raise RuntimeError("scheduled leader failure")
        return PythonOracleBackend().check_scc(
            graph, circuit, scc, scope_to_scc=scope_to_scc
        )


def _run_delta_one(schedule: str, data: object, expected: bool,
                   topology: str) -> ScheduleResult:
    import quorum_intersection_tpu.delta as delta_mod
    from quorum_intersection_tpu.delta import DeltaEngine, SccVerdictStore

    ctl = SyncController()
    # Park the leader between taking its lease and solving, until the
    # follower is provably waiting on that lease.
    ctl.hold("store.leader", ctl.reached_event("store.wait"))
    backend = _CountingOracle(
        fail_first=(schedule == "delta_leader_fails_follower_takes_over")
    )
    engine = DeltaEngine(SccVerdictStore(64), track_diff=False)
    outcomes: Dict[str, object] = {}

    def run(tag: str) -> None:
        try:
            res = engine.check_many([data], backend=backend)
            outcomes[tag] = res[0].intersects
        except Exception as exc:  # noqa: BLE001 — the failure IS the observable
            outcomes[tag] = exc

    old_sync = delta_mod._delta_sync
    delta_mod._delta_sync = ctl
    try:
        # Bounded schedule threads around the pure-python oracle; joined
        # below with a leak check, nothing in-flight to cancel.
        # qi-lint: allow(cancel-token-plumbed) — bounded, joined below
        t1 = threading.Thread(target=run, args=("leader",), daemon=True)
        t1.start()
        if not ctl.reached_event("store.leader").wait(WAIT_S):
            raise ScheduleError("leader never took the lease")
        # qi-lint: allow(cancel-token-plumbed) — bounded, joined below
        t2 = threading.Thread(target=run, args=("follower",), daemon=True)
        t2.start()
        t1.join(WAIT_S)
        t2.join(WAIT_S)
        if t1.is_alive() or t2.is_alive():
            raise ScheduleError(f"schedule {schedule!r} leaked a thread")
    finally:
        delta_mod._delta_sync = old_sync

    error: Optional[str] = None
    verdict = outcomes.get("follower")
    if schedule == "delta_follower_waits_for_leader":
        if backend.calls != 1:
            error = (
                f"single-flight broken: {backend.calls} backend solves for "
                f"two concurrent identical snapshots (want 1)"
            )
        elif outcomes.get("leader") != expected:
            error = f"leader verdict {outcomes.get('leader')} != {expected}"
    else:  # delta_leader_fails_follower_takes_over
        # The failed leader releases its lease (follower re-takes it) and
        # then DEGRADES to the full re-solve chain — it still answers
        # (incremental re-analysis is never a precondition for a verdict).
        if outcomes.get("leader") != expected:
            error = (
                f"failed leader was expected to degrade to the verdict "
                f"{expected}, got {outcomes.get('leader')!r}"
            )
        elif backend.calls != 3:
            error = (
                f"takeover broken: {backend.calls} backend solves (want "
                f"3: failed leader + leader's degraded full re-solve + "
                f"follower retake)"
            )
    if not isinstance(verdict, bool):
        error = error or f"follower reached no verdict: {verdict!r}"
        verdict = not expected
    missing = [
        p for p in _REQUIRED_DELTA_POINTS[schedule] if p not in ctl.trace
    ]
    if error is None and missing:
        error = f"ordering never happened: sync point(s) {missing} not reached"
    return ScheduleResult(
        schedule=schedule,
        topology=topology,
        verdict=bool(verdict),
        expected=expected,
        winner="delta",
        oracle_outcome="-",
        trace=list(ctl.trace),
        error=error,
    )


def run_delta_schedules() -> List[ScheduleResult]:
    """Every delta schedule × {intersecting, broken} topology; ground truth
    from the one-shot pipeline, the differential contract the incremental
    engine is held to everywhere."""
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    results: List[ScheduleResult] = []
    for broken in (False, True):
        data = majority_fbas(9, broken=broken)
        topology = "majority9-broken" if broken else "majority9"
        expected = solve(data, backend="python").intersects
        for schedule in DELTA_SCHEDULES:
            results.append(_run_delta_one(schedule, data, expected, topology))
    return results


# ---- qi-fleet schedules (ISSUE 11) ------------------------------------------
#
# The fleet front door adds a third concurrency surface: routing decisions
# racing ring eviction, and dead-worker journal replay racing new client
# requests for the inherited hash range.  ``fleet._fleet_sync`` is the
# hook, exactly like ``serve._serve_sync``; workers run in-process
# (LocalWorker) so the orderings are forced in milliseconds.

FLEET_SCHEDULES = (
    "fleet_route_during_eviction",
    "fleet_replay_races_new_request",
    "fleet_respawn_restores_ring",
    "fleet_hedge_races_primary_response",
    "fleet_scale_down_races_dispatch",
)

_REQUIRED_FLEET_POINTS: Dict[str, tuple] = {
    # the submit must have resolved its route BEFORE the eviction finished
    # removing that worker from the ring (the dispatch then lands on a
    # dead worker and must re-route, or the failover re-dispatches it).
    "fleet_route_during_eviction": ("route.resolved", "evict.removed"),
    # the failover replay must have started before the new request routed,
    # and both must complete (replay.done) with exactly one outcome each.
    "fleet_replay_races_new_request": (
        "replay.begin", "route.resolved", "replay.done",
    ),
    # the eviction must complete, then the bounded-backoff replacement
    # must actually rejoin the ring (ISSUE 12 satellite) before the
    # post-respawn request serves through the restored ring.
    "fleet_respawn_restores_ring": (
        "evict.removed", "respawn.begin", "respawn.done",
    ),
    # the hedge must have been decided and sent to BOTH legs, and a
    # response must have been delivered WHILE the hedging dispatch was
    # still inside _hedge_dispatch (the hold releases hedge.sent only
    # on response.delivered) — the straggler then deduplicates.
    "fleet_hedge_races_primary_response": (
        "hedge.decided", "hedge.sent", "response.delivered",
    ),
    # the dispatch must have resolved its route to the retiree BEFORE
    # the retirement removed it from the ring (scale.retire); released,
    # it must re-route through the shrunken ring and still deliver.
    "fleet_scale_down_races_dispatch": (
        "route.resolved", "scale.retire", "response.delivered",
    ),
}


def _run_fleet_one(schedule: str, data: object, expected: bool,
                   topology: str) -> ScheduleResult:
    import quorum_intersection_tpu.fleet as fleet_mod
    from quorum_intersection_tpu.fleet import FleetEngine
    from quorum_intersection_tpu.fbas.graph import build_graph
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.serve import (
        RequestJournal,
        snapshot_fingerprint,
    )

    ctl = SyncController()
    verdict: Optional[bool] = None
    error: Optional[str] = None
    old_sync = fleet_mod._fleet_sync
    fleet_mod._fleet_sync = ctl
    engine: Optional[FleetEngine] = None
    tmp = tempfile.TemporaryDirectory(prefix="qi-fleet-sched-")
    try:
        engine = FleetEngine(
            2, backend="python", worker_mode="local",
            journal_dir=tmp.name, probe_interval_s=60.0,
        )
        engine.start()
        fp = snapshot_fingerprint(build_graph(parse_fbas(data)))
        target = engine._ring.route(fp)
        if schedule == "fleet_route_during_eviction":
            # The submit resolves its route to `target`, then parks; the
            # eviction completes meanwhile (ring shrinks, pending requests
            # fail over).  The parked dispatch must NOT deliver to the
            # dead worker — the request still resolves exactly once with
            # the correct verdict, via the failover or the re-route loop.
            ctl.hold("route.resolved", ctl.reached_event("evict.removed"))
            box: Dict[str, object] = {}

            def _submit() -> None:
                try:
                    box["ticket"] = engine.submit(data)
                except Exception as exc:  # noqa: BLE001 — the failure IS the observable
                    box["error"] = exc

            # qi-lint: allow(cancel-token-plumbed) — bounded schedule thread, joined below
            t = threading.Thread(target=_submit, daemon=True)
            t.start()
            if not ctl.reached_event("route.resolved").wait(WAIT_S):
                raise ScheduleError("submit never resolved a route")
            engine.kill_worker(target, evict=True)
            t.join(WAIT_S)
            if t.is_alive():
                raise ScheduleError("submit thread never returned")
            if "error" in box:
                error = f"submit raised {box['error']!r}"
            else:
                res = box["ticket"].result(WAIT_S)  # type: ignore[union-attr]
                verdict = res.intersects
        elif schedule == "fleet_replay_races_new_request":
            # A crashed predecessor's journal holds a pending request for
            # fingerprint X; while its failover replay is parked, a NEW
            # client request for the same X arrives and routes.  Released,
            # the replay re-solves the journaled request on the inheriting
            # peer — the new request must resolve exactly once with the
            # correct verdict and the replayed one must be counted, never
            # duplicated onto the client.
            journal = RequestJournal(Path(tmp.name) / "crashed.journal")
            journal.append_request("ghost-1", fp, data, None)
            journal.close()
            ctl.hold("replay.begin", ctl.reached_event("route.resolved"))
            box2: Dict[str, object] = {}

            def _adopt() -> None:
                try:
                    box2["replayed"] = engine.adopt_journal(journal.path)
                except Exception as exc:  # noqa: BLE001 — the failure IS the observable
                    box2["error"] = exc

            # qi-lint: allow(cancel-token-plumbed) — bounded schedule thread, joined below
            t = threading.Thread(target=_adopt, daemon=True)
            t.start()
            if not ctl.reached_event("replay.begin").wait(WAIT_S):
                raise ScheduleError("adopt_journal never began replaying")
            ticket = engine.submit(data)
            res = ticket.result(WAIT_S)
            verdict = res.intersects
            t.join(WAIT_S)
            if t.is_alive():
                raise ScheduleError("replay thread never returned")
            if "error" in box2:
                error = f"adopt_journal raised {box2['error']!r}"
            elif box2.get("replayed") != 1:
                error = (
                    f"journal replay count {box2.get('replayed')!r} != 1 "
                    f"(pending ghost entry not inherited exactly once)"
                )
        elif schedule == "fleet_respawn_restores_ring":
            # ISSUE 12 satellite: after an eviction the supervisor spawns
            # a bounded-backoff replacement that re-inserts into the ring
            # — the ring must return to full strength and the NEXT
            # request must serve through the restored ring with the
            # correct verdict (pre-respawn the fleet shrank until
            # restart).
            engine.kill_worker(target, evict=True)
            if not ctl.reached_event("respawn.done").wait(WAIT_S):
                raise ScheduleError("respawned worker never rejoined")
            with engine._lock:
                ring_size = len(engine._ring)
            if ring_size != 2:
                error = f"ring size {ring_size} != 2 after respawn"
            else:
                verdict = engine.submit(data).result(WAIT_S).intersects
        elif schedule == "fleet_hedge_races_primary_response":
            # qi-mesh (ISSUE 19): the routed arc owner sits SUSPECTED
            # (missed heartbeats on a live connection), so the dispatch
            # hedges the request to the next arc owner under the SAME
            # wire id.  The hold parks the hedging dispatch between
            # sending both legs and returning, until the FIRST response
            # has already been delivered — the exact window where a
            # suspect that answers races its own hedge.  The client must
            # see exactly one outcome, and the straggler's answer must
            # book fleet.duplicate_responses, never a second resolve.
            from quorum_intersection_tpu.utils.telemetry import (
                get_run_record,
            )

            ctl.hold("hedge.sent", ctl.reached_event("response.delivered"))
            engine._suspect_worker(target, "forced partition (schedule)")
            base = get_run_record().snapshot()[0].get(
                "fleet.duplicate_responses", 0.0,
            )
            ticket = engine.submit(data)
            verdict = ticket.result(WAIT_S).intersects
            # Both legs answer (both workers are healthy local engines):
            # the second answer must land as a deduplicated straggler.
            deadline = time.monotonic() + WAIT_S
            while get_run_record().snapshot()[0].get(
                "fleet.duplicate_responses", 0.0,
            ) < base + 1:
                if time.monotonic() > deadline:
                    error = (
                        "the hedge straggler's answer was never "
                        "deduplicated (fleet.duplicate_responses did "
                        "not move)"
                    )
                    break
                time.sleep(0.002)
        elif schedule == "fleet_scale_down_races_dispatch":
            # qi-mesh (ISSUE 19): a scale-down retirement races a
            # dispatch already routed to the retiree.  _retire_one always
            # picks the reverse-sorted newest worker (w1 here), so pick a
            # fixture whose fingerprint routes to w1, park its dispatch
            # at route.resolved, and drive scale_tick(force=True): the
            # retiree leaves the ring, scale.retire releases the parked
            # dispatch, and it must re-route through the shrunken ring —
            # exactly one verdict, nothing lost to the voluntary shrink.
            from quorum_intersection_tpu.fbas.synth import majority_fbas
            from quorum_intersection_tpu.pipeline import solve

            broken = topology.endswith("-broken")
            data2 = None
            for i in range(64):
                cand = majority_fbas(9, prefix=f"SCALE{i}", broken=broken)
                fp2 = snapshot_fingerprint(build_graph(parse_fbas(cand)))
                if engine._ring.route(fp2) == "w1":
                    data2 = cand
                    break
            if data2 is None:
                raise ScheduleError(
                    "no fixture routing to the retiree (w1) in 64 tries"
                )
            expected = solve(data2, backend="python").intersects
            ctl.hold("route.resolved", ctl.reached_event("scale.retire"))
            box3: Dict[str, object] = {}

            def _submit2() -> None:
                try:
                    box3["ticket"] = engine.submit(data2)
                except Exception as exc:  # noqa: BLE001 — the failure IS the observable
                    box3["error"] = exc

            # qi-lint: allow(cancel-token-plumbed) — bounded schedule thread, joined below
            t = threading.Thread(target=_submit2, daemon=True)
            t.start()
            if not ctl.reached_event("route.resolved").wait(WAIT_S):
                raise ScheduleError("submit never resolved a route")
            decision = engine.scale_tick(force=True)
            t.join(WAIT_S)
            if t.is_alive():
                raise ScheduleError("submit thread never returned")
            if decision != "down":
                error = f"scale tick decided {decision!r}, not 'down'"
            elif "error" in box3:
                error = f"submit raised {box3['error']!r}"
            else:
                with engine._lock:
                    ring_size = len(engine._ring)
                res = box3["ticket"].result(WAIT_S)  # type: ignore[union-attr]
                verdict = res.intersects
                if ring_size != 1:
                    error = f"ring size {ring_size} != 1 after retirement"
        else:
            raise ValueError(f"unknown fleet schedule {schedule!r}")
    finally:
        fleet_mod._fleet_sync = old_sync
        if engine is not None:
            engine.stop(drain=True, timeout=WAIT_S)
        tmp.cleanup()
    missing = [
        p for p in _REQUIRED_FLEET_POINTS[schedule] if p not in ctl.trace
    ]
    if error is None and missing:
        error = f"ordering never happened: sync point(s) {missing} not reached"
    return ScheduleResult(
        schedule=schedule,
        topology=topology,
        verdict=bool(verdict),
        expected=expected,
        winner="fleet",
        oracle_outcome="-",
        trace=list(ctl.trace),
        error=error,
    )


def run_fleet_schedules(join_timeout: float = 5.0) -> List[ScheduleResult]:
    """Every fleet schedule × {intersecting, broken} topology; ground truth
    from the one-shot pipeline, the differential contract the fleet front
    door is held to everywhere.  Leaked drain threads are a failure."""
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    results: List[ScheduleResult] = []
    for broken in (False, True):
        data = majority_fbas(9, broken=broken)
        topology = "majority9-broken" if broken else "majority9"
        expected = solve(data, backend="python").intersects
        for schedule in FLEET_SCHEDULES:
            results.append(_run_fleet_one(schedule, data, expected, topology))
    leaked = [
        t for t in threading.enumerate() if t.name == "qi-serve-drain"
    ]
    for t in leaked:
        t.join(timeout=join_timeout)
    leaked = [t for t in leaked if t.is_alive()]
    if leaked:
        raise ScheduleError(
            f"{len(leaked)} serve drain thread(s) still alive after "
            f"{join_timeout}s — a fleet schedule leaked a worker engine"
        )
    return results


# ---- qi-fuse batch-former schedules (ISSUE 16) ------------------------------
#
# The serve drain's cross-request BatchFormer (fuse.py) adds one more
# concurrency surface: producers from different requests race the elected
# flusher.  The ordering worth forcing is a LATE submit landing while a
# flush is already formed — the late unit must ride the NEXT flush with a
# correct result, never be dropped into the in-flight batch or lost.
# ``fuse._fuse_sync`` is the hook, exactly like ``serve._serve_sync``.

FUSE_SCHEDULES = (
    "fuse_flush_races_late_submit",
)

_REQUIRED_FUSE_POINTS: Dict[str, tuple] = {
    # The late producer must have entered submit while the first flush
    # was formed-but-held, and a second flush must have completed.
    "fuse_flush_races_late_submit": (
        "fuse.submit", "fuse.flush.formed", "fuse.flush.done",
    ),
}


def _run_fuse_one(schedule: str, data: object, expected: bool,
                  topology: str) -> ScheduleResult:
    import quorum_intersection_tpu.fuse as fuse_mod
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.fuse import BatchFormer
    from quorum_intersection_tpu.pipeline import check_many

    ctl = SyncController()
    release = threading.Event()
    verdict: Optional[bool] = None
    error: Optional[str] = None
    old_sync = fuse_mod._fuse_sync
    fuse_mod._fuse_sync = ctl
    workers: List[threading.Thread] = []
    try:
        if schedule == "fuse_flush_races_late_submit":
            # Producer A's flush is formed (batch snapshotted, lock
            # released) and HELD; producer B submits meanwhile.  B's unit
            # must land in the next flush — two flushes total, both
            # verdicts correct.
            ctl.hold("fuse.flush.formed", release)
            former = BatchFormer(
                lambda sources, cancels, origins: check_many(
                    sources, backend="python",
                ),
                window_ms=60_000.0,  # timer effectively off: drain flushes
            )
            outcomes: Dict[str, object] = {}

            def producer(name: str, source: object) -> None:
                former.register()
                try:
                    outcomes[name] = former.submit(
                        [parse_fbas(source)], origin=name,
                    )[0]
                except BaseException as exc:  # noqa: BLE001 — recorded, re-raised as schedule error
                    outcomes[name] = exc
                finally:
                    former.done()

            t_a = threading.Thread(
                target=producer, args=("A", data), name="qi-fuse-sched-a",
            )
            workers.append(t_a)
            t_a.start()
            if not ctl.reached_event("fuse.flush.formed").wait(WAIT_S):
                raise ScheduleError("first flush never formed")
            t_b = threading.Thread(
                target=producer,
                args=("B", majority_fbas(7, prefix="LATE", broken=False)),
                name="qi-fuse-sched-b",
            )
            workers.append(t_b)
            t_b.start()
            deadline = time.monotonic() + WAIT_S
            while ctl.trace.count("fuse.submit") < 2:
                if time.monotonic() > deadline:
                    raise ScheduleError("late submit never queued")
                time.sleep(0.002)
            release.set()
            for t in workers:
                t.join(WAIT_S)
            res_a, res_b = outcomes.get("A"), outcomes.get("B")
            if isinstance(res_a, BaseException) or res_a is None:
                error = f"producer A failed: {res_a!r}"
            elif isinstance(res_b, BaseException) or res_b is None:
                error = f"late producer B failed: {res_b!r}"
            elif len(former.flush_log) != 2:
                error = (
                    f"expected 2 flushes (early batch + late unit), got "
                    f"{former.flush_log!r}"
                )
            elif res_b.intersects is not True:
                error = "late producer's majority-7 verdict flipped"
            else:
                verdict = res_a.intersects
        else:
            raise ValueError(f"unknown fuse schedule {schedule!r}")
    finally:
        fuse_mod._fuse_sync = old_sync
        release.set()
        for t in workers:
            t.join(timeout=WAIT_S)
    missing = [
        p for p in _REQUIRED_FUSE_POINTS[schedule] if p not in ctl.trace
    ]
    if error is None and missing:
        error = f"ordering never happened: sync point(s) {missing} not reached"
    return ScheduleResult(
        schedule=schedule,
        topology=topology,
        verdict=bool(verdict),
        expected=expected,
        winner="fuse",
        oracle_outcome="-",
        trace=list(ctl.trace),
        error=error,
    )


def run_fuse_schedules(join_timeout: float = 5.0) -> List[ScheduleResult]:
    """Every fuse schedule × {intersecting, broken} topology; ground truth
    from the one-shot pipeline (the byte-parity contract the fused drain
    is held to).  Leaked producer threads are a failure."""
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    results: List[ScheduleResult] = []
    for broken in (False, True):
        data = majority_fbas(9, broken=broken)
        topology = "majority9-broken" if broken else "majority9"
        expected = solve(data, backend="python").intersects
        for schedule in FUSE_SCHEDULES:
            results.append(_run_fuse_one(schedule, data, expected, topology))
    leaked = [
        t for t in threading.enumerate()
        if t.name.startswith("qi-fuse-sched-")
    ]
    for t in leaked:
        t.join(timeout=join_timeout)
    leaked = [t for t in leaked if t.is_alive()]
    if leaked:
        raise ScheduleError(
            f"{len(leaked)} fuse producer thread(s) still alive after "
            f"{join_timeout}s — a schedule leaked its former"
        )
    return results


# ---- qi-cost adaptive-window schedules (ISSUE 17) ---------------------------
#
# The pulse-driven fuse-window controller (cost.choose_fuse_window, called
# from the serve drain's _auto_fuse_window) adds one more ordering surface:
# an admission landing WHILE a window decision is in flight.  The late
# request must ride the next drain cycle and earn its OWN decision — never
# wedge behind a held controller, never silently inherit the in-flight
# batch.  ``cost._cost_sync`` is the hook, exactly like serve/fuse's.

COST_SCHEDULES = (
    "cost_window_decision_races_late_admit",
)

_REQUIRED_COST_POINTS: Dict[str, tuple] = {
    # The controller must have decided at least twice: once for the batch
    # it was held on, once for the late admission's own drain cycle.
    "cost_window_decision_races_late_admit": ("cost.window.decide",),
}


def _run_cost_one(schedule: str, data: object, expected: bool,
                  topology: str) -> ScheduleResult:
    import quorum_intersection_tpu.cost as cost_mod
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.serve import ServeEngine

    ctl = SyncController()
    release = threading.Event()
    verdict: Optional[bool] = None
    error: Optional[str] = None
    old_sync = cost_mod._cost_sync
    cost_mod._cost_sync = ctl
    engine: Optional[ServeEngine] = None
    try:
        if schedule == "cost_window_decision_races_late_admit":
            # The drain pops request A and its window decision is HELD
            # mid-flight; request B is admitted meanwhile.  On release, A
            # must drain with the held decision's window, B must pop on
            # the NEXT cycle with a fresh decision — two decisions in the
            # trace, both verdicts correct.
            ctl.hold("cost.window.decide", release)
            engine = ServeEngine(
                backend="python", fuse_window_ms="auto", batch_max=1,
                queue_depth=8,
            )
            ticket_a = engine.submit(data)
            engine.start()
            if not ctl.reached_event("cost.window.decide").wait(WAIT_S):
                raise ScheduleError("window decision never reached")
            ticket_b = engine.submit(majority_fbas(7, prefix="LATE"))
            release.set()
            resp_a = ticket_a.result(timeout=WAIT_S)
            resp_b = ticket_b.result(timeout=WAIT_S)
            engine.stop(drain=True, timeout=WAIT_S)
            if ctl.trace.count("cost.window.decide") < 2:
                error = (
                    f"late admission never earned its own window decision "
                    f"(trace {ctl.trace!r})"
                )
            elif resp_b.intersects is not True:
                error = "late request's majority-7 verdict flipped"
            else:
                verdict = resp_a.intersects
        else:
            raise ValueError(f"unknown cost schedule {schedule!r}")
    finally:
        cost_mod._cost_sync = old_sync
        release.set()
        if engine is not None:
            engine.stop(drain=False, timeout=WAIT_S)
    missing = [
        p for p in _REQUIRED_COST_POINTS[schedule] if p not in ctl.trace
    ]
    if error is None and missing:
        error = f"ordering never happened: sync point(s) {missing} not reached"
    return ScheduleResult(
        schedule=schedule,
        topology=topology,
        verdict=bool(verdict),
        expected=expected,
        winner="cost",
        oracle_outcome="-",
        trace=list(ctl.trace),
        error=error,
    )


def run_cost_schedules(join_timeout: float = 5.0) -> List[ScheduleResult]:
    """Every cost schedule × {intersecting, broken} topology; ground truth
    from the one-shot pipeline.  Leaked drain threads are a failure."""
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    results: List[ScheduleResult] = []
    for broken in (False, True):
        data = majority_fbas(9, broken=broken)
        topology = "majority9-broken" if broken else "majority9"
        expected = solve(data, backend="python").intersects
        for schedule in COST_SCHEDULES:
            results.append(_run_cost_one(schedule, data, expected, topology))
    leaked = [
        t for t in threading.enumerate()
        if t.name.startswith("qi-serve-drain")
    ]
    for t in leaked:
        t.join(timeout=join_timeout)
    leaked = [t for t in leaked if t.is_alive()]
    if leaked:
        raise ScheduleError(
            f"{len(leaked)} serve drain thread(s) still alive after "
            f"{join_timeout}s — a cost schedule leaked its engine"
        )
    return results


def run_all(join_timeout: float = 5.0) -> List[ScheduleResult]:
    """Every schedule × {intersecting, broken} topology.  The expected
    verdict is computed by the sequential (race=False) chain with the real
    engines — the ground truth every forced interleaving must reproduce.
    Leaked race workers are a failure, not a warning."""
    from quorum_intersection_tpu.backends.auto import AutoBackend
    from quorum_intersection_tpu.fbas.synth import majority_fbas
    from quorum_intersection_tpu.pipeline import solve

    results: List[ScheduleResult] = []
    for broken in (False, True):
        data = majority_fbas(9, broken=broken)
        topology = "majority9-broken" if broken else "majority9"
        expected = solve(data, backend=AutoBackend(race=False)).intersects
        for schedule in SCHEDULES:
            results.append(_run_one(schedule, data, expected, topology))
    leaked = [
        t for t in threading.enumerate() if t.name == "qi-race-sweep"
    ]
    for t in leaked:
        t.join(timeout=join_timeout)
    leaked = [t for t in leaked if t.is_alive()]
    if leaked:
        raise ScheduleError(
            f"{len(leaked)} race worker thread(s) still alive after "
            f"{join_timeout}s — a schedule leaked its loser"
        )
    return results
