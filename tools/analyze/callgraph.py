"""Shared call-edge machinery for the interprocedural analyzer passes.

Factored out of ``tools/analyze/locks.py`` (ISSUE 18) so the lock pass,
the device-hygiene pass and the conservation pass resolve call edges
identically: ``self.m()`` methods, module functions, cross-module
imports within the analyzed file set, typed ``self.attr.m()`` instance
attributes (``self.X = ClassName(...)``), nested defs, and a
unique-method-name fallback that refuses builtin-collection collisions.

Two layers live here:

- :class:`CallGraph` — the resolution core (``resolve``) over the duck
  shape locks' ``Model`` already had: ``functions`` keyed by
  :data:`FnKey`, ``classes`` whose values expose ``.methods``,
  ``imports``, ``method_index``.  ``locks.Model`` now subclasses it.
- :class:`PackageGraph` / :func:`build_graph` — a lightweight
  whole-package graph (per-function call lists + telemetry span names)
  used by the hygiene and conserve passes, where lock semantics are
  irrelevant but reachability ("is this function on a hot path, and
  via which call chain?") is the whole game.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from tools.analyze.lint import FileContext, resolve_name_arg

FnKey = Tuple[str, str]  # (rel path, qualname)

# Method names the unique-name call-resolution fallback must never claim:
# they collide with builtin container/file/threading APIs (``counters.get``
# is a dict read, not SharedSccStore.get), and a wrong edge invents
# reachability (or a deadlock cycle) out of thin air.  Typed receivers
# (``self.X`` whose class is known from its constructor assignment) still
# resolve these precisely.
AMBIGUOUS_METHODS = frozenset({
    "get", "add", "pop", "append", "appendleft", "popleft", "update",
    "clear", "extend", "remove", "discard", "insert", "setdefault", "keys",
    "values", "items", "copy", "join", "split", "strip", "sort", "index",
    "count", "read", "write", "close", "flush", "open", "set", "wait",
    "notify", "notify_all", "acquire", "release", "put", "send", "recv",
    "emit", "finish", "start", "stop", "run", "scan",
})

_THREADING_CTORS = frozenset({"Lock", "RLock", "Condition", "Event", "Thread"})


@dataclass(frozen=True)
class CallRef:
    """An unresolved callee reference, resolved against a whole graph."""

    kind: str          # "self" | "name" | "attr" | "instattr"
    name: str
    rel: str           # referencing file
    cls: Optional[str] = None  # class of the referencing method


def threading_call(node: ast.AST, names: Iterable[str]) -> Optional[str]:
    """``threading.X(...)`` / bare ``X(...)`` for X in names → X."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    name = f.attr if isinstance(f, ast.Attribute) else (
        f.id if isinstance(f, ast.Name) else None
    )
    return name if name in set(names) else None


def ctor_name(call: ast.AST) -> Optional[str]:
    """Capitalized constructor name of ``X(...)`` / ``mod.X(...)``, else None."""
    if not isinstance(call, ast.Call):
        return None
    f = call.func
    ctor = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None
    )
    if ctor is not None and ctor[:1].isupper():
        return ctor
    return None


def instance_attrs(cls: ast.ClassDef) -> Dict[str, str]:
    """``self.X = ClassName(...)`` attrs → class name, threading ctors excluded.

    The typed-receiver map behind ``instattr`` resolution: a later
    ``self.X.m()`` resolves to ``ClassName.m`` wherever that class lives
    in the analyzed file set.
    """
    out: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (isinstance(tgt, ast.Attribute) and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            continue
        if threading_call(node.value, _THREADING_CTORS) is not None:
            continue
        ctor = ctor_name(node.value)
        if ctor is not None:
            out[tgt.attr] = ctor
    return out


def ref_of(expr: ast.AST, rel: str, cls_name: Optional[str],
           instances: Dict[str, str]) -> Optional[CallRef]:
    """Classify a callee expression into a :class:`CallRef` (or None)."""
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return CallRef("self", expr.attr, rel, cls_name)
    if isinstance(expr, ast.Attribute) \
            and isinstance(expr.value, ast.Attribute) \
            and isinstance(expr.value.value, ast.Name) \
            and expr.value.value.id == "self":
        inst_cls = instances.get(expr.value.attr)
        if inst_cls is not None:
            return CallRef("instattr", f"{inst_cls}.{expr.attr}", rel, cls_name)
    if isinstance(expr, ast.Name):
        return CallRef("name", expr.id, rel, cls_name)
    if isinstance(expr, ast.Attribute):
        return CallRef("attr", expr.attr, rel, cls_name)
    return None


def module_rel_map(rels: Iterable[str]) -> Dict[str, str]:
    """Dotted module path → rel path for the analyzed file set."""
    return {rel[:-3].replace("/", "."): rel for rel in rels}


def collect_imports(rel: str, tree: ast.Module, rel_by_module: Dict[str, str],
                    deep: bool = False) -> Dict[Tuple[str, str], str]:
    """``from mod import name`` edges landing inside the analyzed set.

    ``deep=True`` also walks function bodies (the repo's lazy local
    imports — ``query.py`` imports the analytics resolvers inside the
    resolving method), which the hot-region map needs; the locks pass
    keeps the historical top-level-only view.
    """
    out: Dict[Tuple[str, str], str] = {}
    nodes: Iterable[ast.AST] = ast.walk(tree) if deep else tree.body
    for node in nodes:
        if isinstance(node, ast.ImportFrom) and node.module:
            target_rel = rel_by_module.get(node.module)
            if target_rel is not None:
                for alias in node.names:
                    out[(rel, alias.asname or alias.name)] = target_rel
    return out


def iter_defs(tree: ast.Module) -> Iterable[Tuple[str, Optional[str], ast.AST]]:
    """Yield ``(qualname, class name or None, def node)`` for a module.

    Locks' exact registration scheme: top-level functions, class methods,
    and nested defs one level below either (qual ``outer.inner``), first
    qualname wins on duplicates.
    """
    seen: Set[str] = set()

    def register(fn_node: ast.AST, qual: str, cls: Optional[str],
                 out: List[Tuple[str, Optional[str], ast.AST]]) -> None:
        if qual in seen:
            return
        seen.add(qual)
        out.append((qual, cls, fn_node))
        for stmt in ast.walk(fn_node):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt is not fn_node \
                    and f"{qual}.{stmt.name}" not in seen:
                seen.add(f"{qual}.{stmt.name}")
                out.append((f"{qual}.{stmt.name}", cls, stmt))

    out: List[Tuple[str, Optional[str], ast.AST]] = []
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    register(sub, f"{node.name}.{sub.name}", node.name, out)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            register(node, node.name, None, out)
    return out


class CallGraph:
    """Resolution core shared by every interprocedural pass.

    Subclasses populate ``functions`` / ``classes`` / ``imports`` /
    ``method_index``; ``classes`` values must expose ``.methods``
    (a set of method names) — both locks' ``ClassModel`` and
    :class:`ClassInfo` do.
    """

    def __init__(self) -> None:
        self.classes: Dict[Tuple[str, str], object] = {}
        self.functions: Dict[FnKey, object] = {}
        self.module_fns: Dict[str, Set[str]] = {}
        self.imports: Dict[Tuple[str, str], str] = {}
        self.method_index: Dict[str, List[FnKey]] = {}
        self.ctxs: Dict[str, FileContext] = {}

    def resolve(self, ref: CallRef) -> Optional[FnKey]:
        if ref.kind == "self" and ref.cls is not None:
            key = (ref.rel, f"{ref.cls}.{ref.name}")
            if key in self.functions:
                return key
            return None
        if ref.kind == "name":
            if (ref.rel, ref.name) in self.imports:
                target_rel = self.imports[(ref.rel, ref.name)]
                key = (target_rel, ref.name)
                return key if key in self.functions else None
            key = (ref.rel, ref.name)
            if key in self.functions:
                return key
            # nested function of some scope in the same file
            for cand_key in self.functions:
                if cand_key[0] == ref.rel and cand_key[1].endswith(
                        f".{ref.name}"):
                    return cand_key
            return None
        if ref.kind == "instattr":
            # self.<attr>.<method>() with the attr's class known from its
            # constructor assignment
            cls_name, method = ref.name.split(".", 1)
            for (rel, name), cls in self.classes.items():
                if name == cls_name and method in getattr(cls, "methods", set()):
                    return (rel, f"{name}.{method}")
            return None
        # attribute call on an unknown receiver: unique-method-name
        # fallback, builtin-collection collisions excluded
        if ref.name in AMBIGUOUS_METHODS:
            return None
        cands = self.method_index.get(ref.name, [])
        if len(cands) == 1:
            return cands[0]
        return None


# ---------------------------------------------------------------------------
# whole-package graph (hygiene / conserve consumer)


@dataclass
class ClassInfo:
    """Minimal class shape the resolution core needs."""

    name: str
    rel: str
    methods: Set[str] = field(default_factory=set)
    instances: Dict[str, str] = field(default_factory=dict)


@dataclass
class FnInfo:
    """One function body: its call edges and the telemetry spans it opens."""

    key: FnKey
    cls_name: Optional[str]
    node: ast.AST
    calls: List[Tuple[CallRef, int]] = field(default_factory=list)
    spans: Set[str] = field(default_factory=set)


class PackageGraph(CallGraph):
    """Call graph over an arbitrary file set, spans attached per function."""

    def __init__(self) -> None:
        super().__init__()
        self.infos: Dict[FnKey, FnInfo] = {}

    def span_owners(self, span: str) -> List[FnKey]:
        """Functions whose body opens the named telemetry span."""
        return sorted(k for k, fn in self.infos.items() if span in fn.spans)


def _scan_fn(graph: PackageGraph, info: FnInfo, ctx: FileContext,
             instances: Dict[str, str]) -> None:
    rel = info.key[0]
    for node in ast.walk(info.node):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not info.node:
            continue  # nested defs are modeled as their own functions
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "span" and node.args:
            name = resolve_name_arg(ctx, node.args[0])
            if name:
                info.spans.add(name.rstrip("*"))
        ref = ref_of(f, rel, info.cls_name, instances)
        if ref is not None:
            info.calls.append((ref, node.lineno))
        # callables passed by reference (thread targets, callbacks,
        # registered resolvers) are edges too — they run eventually
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            if isinstance(arg, (ast.Attribute, ast.Name)):
                aref = ref_of(arg, rel, info.cls_name, instances)
                if aref is not None:
                    info.calls.append((aref, node.lineno))


def build_graph(root: Path, targets: Sequence[str]) -> PackageGraph:
    """Build a :class:`PackageGraph` over ``targets`` (rel paths)."""
    graph = PackageGraph()
    trees: List[Tuple[str, ast.Module, FileContext]] = []
    for rel in targets:
        path = root / rel
        if not path.is_file():
            continue
        try:
            source = path.read_text(encoding="utf-8")
            ctx = FileContext(path, rel, source)
        except (OSError, SyntaxError):
            continue
        graph.ctxs[rel] = ctx
        trees.append((rel, ctx.tree, ctx))
    rel_by_module = module_rel_map(rel for rel, _, _ in trees)
    instances_by_cls: Dict[Tuple[str, str], Dict[str, str]] = {}
    for rel, tree, _ in trees:
        graph.module_fns[rel] = set()
        graph.imports.update(collect_imports(rel, tree, rel_by_module,
                                             deep=True))
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                info = ClassInfo(name=node.name, rel=rel,
                                 instances=instance_attrs(node))
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        info.methods.add(sub.name)
                graph.classes[(rel, node.name)] = info
                instances_by_cls[(rel, node.name)] = info.instances
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                graph.module_fns[rel].add(node.name)
        for qual, cls_name, fn_node in iter_defs(tree):
            info = FnInfo(key=(rel, qual), cls_name=cls_name, node=fn_node)
            graph.functions[info.key] = info
            graph.infos[info.key] = info
    for key, info in graph.infos.items():
        graph.method_index.setdefault(key[1].split(".")[-1], []).append(key)
    for info in graph.infos.values():
        instances = instances_by_cls.get((info.key[0], info.cls_name or ""), {})
        _scan_fn(graph, info, graph.ctxs[info.key[0]], instances)
    return graph


def reachable(graph: PackageGraph, seeds: Dict[FnKey, str],
              ) -> Dict[FnKey, Tuple[str, Tuple[str, ...]]]:
    """BFS closure of ``seeds`` with witness chains.

    Returns key → ``(seed label, call chain of qualnames)``; the chain is
    the shortest span-seeded path that makes the function hot, rendered
    into every hygiene finding so a reader can check the reachability
    claim instead of trusting it.
    """
    out: Dict[FnKey, Tuple[str, Tuple[str, ...]]] = {}
    frontier: List[FnKey] = []
    for key in sorted(seeds):
        if key in graph.infos and key not in out:
            out[key] = (seeds[key], (key[1],))
            frontier.append(key)
    while frontier:
        key = frontier.pop(0)
        label, chain = out[key]
        for ref, _line in graph.infos[key].calls:
            callee = graph.resolve(ref)
            if callee is None or callee in out or callee not in graph.infos:
                continue
            out[callee] = (label, chain + (callee[1],))
            frontier.append(callee)
    return out
