#!/bin/bash
# Round-5 follow-up measurements (run after tools/onchip_r5.sh):
#   1. sweep-vs-native rows — the artifact that raises auto's accelerator
#      sweep limit (backends/calibration.py sweep window) and records the
#      engine that actually wins the mid-range on this chip;
#   2. a wide-sweep run with a kill EARLY enough to really fire (the r5
#      2^36 run finished in 92 s, before the 120 s kill; VERDICT §next-6
#      wants a real on-chip SIGKILL + resume);
#   3. frontier win-region rows under pop=2048 — the frontier_scaling
#      sweet spot (hier-6x4: 5.5 s vs 25.5 s at the default config) —
#      to widen the measured win region if scc 28 flips too.
# Same discipline as onchip_r5.sh: probe before every step, unbuffered,
# tee'd, timeouts everywhere — plus pipefail so a step killed mid-pipe
# fails the script instead of exiting 0 through tee (r5 review finding;
# a caller like tunnel_watch.sh keys "sequence COMPLETE" off rc=0).
set -x
set -o pipefail
cd "$(dirname "$0")/.."
R=benchmarks/results

probe() {
    timeout 100 python -c "import jax; print(jax.devices())" || {
        echo "tunnel down before: $1" >&2; exit 1; }
}

rc=0

probe sweep_vs_native
timeout 3600 python -u benchmarks/sweep_vs_native.py --native-cap 900 \
    2>&1 | tee "$R/sweep_vs_native_tpu_r5.txt" || rc=1

probe wide_kill
timeout 1800 python -u tools/wide_run.py --bits 36 --kill-after 45 \
    --resume-lo-bits 28 --tag r5kill || rc=1

probe crossover_pop2048
timeout 1800 python -u benchmarks/hybrid_crossover.py --large-only --pop 2048 \
    2>&1 | tee -a "$R/crossover_tpu_r5.txt" || rc=1

exit $rc
