#!/bin/bash
# Round-5 third chip pass: complete the native oracle at scc 36 (~21 min
# single-core) so the sweep window's largest win is MEASURED, not
# estimated — appended to the SAME round artifact (calibration skips the
# earlier estimate-only row and takes the completed ratio; r5c in a new
# file name would tie on round rank and be ignored).
set -x
set -o pipefail
cd "$(dirname "$0")/.."
R=benchmarks/results

timeout 100 python -c "import jax; print(jax.devices())" || {
    echo "tunnel down" >&2; exit 1; }
timeout 2400 python -u benchmarks/sweep_vs_native.py --scc 36 --native-cap 1400 \
    2>&1 | tee -a "$R/sweep_vs_native_tpu_r5.txt"
