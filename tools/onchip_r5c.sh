#!/bin/bash
# Round-5 third chip pass: try to complete the native oracle at scc 36
# so the sweep window's largest win is MEASURED, not estimated — appended
# to the SAME round artifact (calibration skips the earlier estimate-only
# row and takes the completed ratio; r5c in a new file name would tie on
# round rank and be ignored).
#
# MEASURED REALITY (r5): two attempts (cap 1400, then cap 2000 with a
# 3000 s outer timeout) both failed to complete the native run — the
# 4.66x-per-+4-orgs extrapolation of the B&B call count UNDERESTIMATES
# above scc 32 (the measured +4 growth was 29.7x at 24→28, then 4.66x at
# 28→32; the law is irregular), so the true scc-36 search exceeded 50
# minutes of single-core time where the model said ~26.  The caps below
# budget for ~2x the model; even a failed run still measures a floor.
set -x
set -o pipefail
cd "$(dirname "$0")/.."
R=benchmarks/results

timeout 100 python -c "import jax; print(jax.devices())" || {
    echo "tunnel down" >&2; exit 1; }
timeout 7200 python -u benchmarks/sweep_vs_native.py --scc 36 --native-cap 4000 \
    2>&1 | tee -a "$R/sweep_vs_native_tpu_r5.txt"
