"""In-process randomized fuzz of the PYTHON parsing/solving surface.

tools/fuzz_native.py proved the class is real (it caught a native
heap-buffer-overflow in its first 30 cases); this is the same generator
suite pointed at the Python side, in-process so the image's expensive
interpreter startup (sitecustomize imports jax into every child) is paid
once instead of per case.

Contract per case:

- ``parse_fbas(payload)`` either succeeds or raises ``ValueError``
  (``FbasSchemaError`` / ``json.JSONDecodeError`` both derive from it —
  exactly what cli.py maps to ``invalid FBAS configuration``).  Any other
  exception type (KeyError, TypeError, RecursionError, ...) is a bug: the
  CLI would print a traceback instead of the clean diagnostic.
- on successful parse: ``build_graph`` + a full ``solve`` (native oracle)
  must yield a boolean verdict without raising.
- the sanitizer (``fbas.sanitize.sanitize``) must likewise either
  produce output or raise ``ValueError`` — it fronts the same untrusted
  stdin in production.

Appends to ``benchmarks/results/fuzz_python_ledger.json`` (soak-style,
windows keyed by (seed, cases), skipped when already recorded).

Usage::

    JAX_PLATFORMS=cpu python tools/fuzz_python.py --cases 5000 --seed 0
"""

from __future__ import annotations

import argparse
import json
import pathlib
import random
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from tools.fuzz_native import make_random_json, make_valid, mutate  # noqa: E402

LEDGER = pathlib.Path(__file__).resolve().parent.parent / (
    "benchmarks/results/fuzz_python_ledger.json"
)


def main() -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--cases", type=int, default=5000)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--force", action="store_true")
    parser.add_argument("--no-ledger", action="store_true")
    args = parser.parse_args()

    from quorum_intersection_tpu.utils.platform import honor_platform_env

    honor_platform_env()

    from quorum_intersection_tpu.fbas.graph import build_graph
    from quorum_intersection_tpu.fbas.sanitize import sanitize
    from quorum_intersection_tpu.fbas.schema import parse_fbas
    from quorum_intersection_tpu.pipeline import solve

    ledger = {"windows": [], "cumulative_cases": 0, "failures": []}
    if LEDGER.exists():
        ledger = json.loads(LEDGER.read_text())
    window_key = [args.seed, args.cases]
    if not args.force and any(
        w["window"] == window_key for w in ledger["windows"]
    ):
        print(f"window {window_key} already recorded; --force to redo")
        return 0

    rng = random.Random(args.seed)
    t0 = time.time()
    counts = {"valid": 0, "mutated": 0, "random-json": 0}
    outcomes = {"parsed+solved": 0, "clean-reject": 0}
    failures = []
    for i in range(args.cases):
        roll = rng.random()
        if roll < 0.2:
            kind, payload = "valid", make_valid(rng)
        elif roll < 0.7:
            kind, payload = "mutated", mutate(rng, make_valid(rng))
        else:
            kind, payload = "random-json", make_random_json(rng)
        counts[kind] += 1

        stage = "parse"
        try:
            fbas = parse_fbas(payload)
            stage = "sanitize"
            sanitize(json.loads(payload))
            stage = "graph"
            graph = build_graph(fbas)
            stage = "solve"
            res = solve(payload, backend="cpp")
            assert res.intersects in (True, False)
            del graph
            outcomes["parsed+solved"] += 1
        except ValueError:
            # Clean rejection — includes FbasSchemaError and JSON errors;
            # any parse that got past json.loads may still cleanly reject
            # at a later stage (e.g. depth caps at graph/solve time).
            outcomes["clean-reject"] += 1
        except Exception as exc:  # noqa: BLE001 — the finding this hunts
            failures.append({
                "case": i, "kind": kind, "stage": stage,
                "exc": f"{type(exc).__name__}: {exc}"[:300],
                "payload_head": payload[:200],
            })
        if (i + 1) % 1000 == 0:
            print(f"  ... {i + 1}/{args.cases} "
                  f"({time.time() - t0:.0f}s, {len(failures)} failures)",
                  flush=True)

    record = {
        "window": window_key, "cases": args.cases, "by_kind": counts,
        "outcomes": outcomes, "n_failures": len(failures),
        "seconds": round(time.time() - t0, 1),
    }
    print(json.dumps(record), flush=True)
    for f in failures[:20]:
        print("FAILURE:", json.dumps(f), flush=True)
    if not args.no_ledger:
        ledger["windows"].append(record)
        ledger["cumulative_cases"] += args.cases
        ledger["failures"].extend(failures)
        LEDGER.write_text(json.dumps(ledger, indent=1))
        print(f"ledger: {ledger['cumulative_cases']} cumulative cases, "
              f"{len(ledger['failures'])} failures -> {LEDGER}")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
